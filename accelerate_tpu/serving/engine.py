"""Continuous-batching serving engine over the compiled generation stack.

The TPU constraint (GSPMD: peak performance comes from a small number of
fixed-shape compiled programs) shapes the whole design. The engine owns a
fixed ``[max_slots, max_len]`` decode state — per-slot KV cache, write
position, carry rng, and eos latch — and after warmup runs a FIXED set of
compiled programs, no matter how requests arrive or leave:

* ``prefill_chunk`` — ONE compiled executable of fixed shape
  ``[1, prefill_chunk]`` serves every prompt length: a prompt is a
  sequence of identical-shape chunk calls at traced ``cache_pos =
  offset`` (slot index, chunk offset, and true length are all traced
  arguments, never shapes). The tail chunk is EDGE-padded on the host
  (numpy, so no per-length jnp pad programs); the executable reads the
  logits row of ``true_len - 1`` mapped into the chunk window, and also
  returns the chunk's own KV block so the prefix cache never needs a
  separate extract program. Warmup therefore leaves ZERO lazy compiles
  for any prompt length — there is no per-bucket prefill family anymore.
* ``decode_step_all_slots`` — one token for every slot per tick, a
  ``jax.vmap`` of the batch-1 single-token forward over the slot axis,
  sharing :func:`generation._next_token` with the offline scan so engine
  streams are bit-identical to offline :func:`generation.generate` for the
  same (prompt, rng, sampling). Slot membership is a host-provided boolean
  mask ARGUMENT, never a shape: admitting or retiring a request changes
  the mask bits, not the program.
* ``restore_prefix`` — one compiled copy of a cached ``[1, prefill_chunk]``
  KV block into a slot's cache at a traced offset, so a prompt whose
  chunk-aligned prefix is in the :class:`scheduler.PrefixCache` (shared
  system prompts, few-shot headers) skips those chunks' prefill FLOPs
  entirely and resumes chunking at the boundary.

Mesh-sliced mode (``tp=`` / ``mesh=``): the same three programs compile
with ``in_shardings``/``out_shardings`` from
:class:`~.mesh_exec.SliceExec`, so one engine spans a tensor-parallel
slice of devices — params in the Megatron column/row layout the training
side uses, the KV cache sharded on its heads axis, the adapter bank
matching its base kernels — while slot membership, pos/tok/rng/done
rows, prompt chunks, and masks stay replicated *data*. Nothing about the
zero-recompile discipline changes: membership is still a traced
argument, the warm-executable count is still three, and streams are
token-identical to the single-chip engine. Prefix-cache blocks are
fetched to host numpy in this mode, so one :class:`PrefixCache` can be
shared by every slice of a ``ReplicaSet.from_mesh`` fleet (a block saved
by one slice restores into any other's shardings — cross-slice hits
survive failover).

Admission is interleaved, not monolithic: an admitted request sits in
``PREFILLING`` holding its slot, and each scheduler iteration spends at
most ``prefill_chunks_per_tick`` chunk calls (round-robin across the
prefill backlog) before the next decode tick — so decode lanes advance
every tick and a 4k-token arrival can no longer stall every active
stream for its whole prefill. Outputs stay token-identical to the
monolithic path and to offline ``generate``: chunking changes WHEN KV is
written, not what is written, and the first-token rng split
(:func:`generation._chunk_prefill_token`) is the same.

Pad/garbage-KV safety, chunked edition: chunk calls write KV in place
into the slot's region of the shared cache, which may hold a previous
occupant's entries (and the tail chunk writes edge-pad KV past
``true_len``). Both are safe for the same reason the offline bucketing
is: the attention mask attends ``k_pos <= q_pos`` only, and masking is
REPLACEMENT (``jnp.where(mask, logits, -1e30)``), so a masked garbage
key contributes exactly 0 probability — finite garbage KV never changes
a real row's output. Positions at/past ``true_len`` are overwritten by
the first decode write at-or-before the first query that could attend
them. One extra invariant protects ``PREFILLING`` slots from the decode
tick (whose cache commit is unconditional): every ``prefill_chunk`` and
``restore_prefix`` call writes ``pos[slot] = true_len``, so any garbage
a tick writes for a mid-prefill slot lands at ``true_len`` — a position
no prompt chunk reads and the first real decode write overwrites.

Paged KV memory (``paged=True``, the default with chunked prefill): the
dense per-slot rows are replaced by a global pool of fixed-size KV PAGES
(``page_size`` tokens, default one prefill chunk) plus a host-side
``[max_slots, max_pages_per_slot]`` page table. The table rides into the
SAME warm executables as traced integer data — the prefill chunk, decode
tick, and restore programs gather each slot's pages into a dense view,
run the unchanged forward, and scatter only the written pages back — so
page allocation, free, preemption, and prefix-block ALIASING (a cache
hit becomes a host table write + refcount, zero device copies) all
compile nothing. Page 0 is a reserved scratch page: unallocated table
entries point at it, so clamped gathers/scatters for inactive slots land
there harmlessly (garbage KV is masked or overwritten, the same
invariant as the dense path). Short requests now hold pages, not
worst-case rows — severalfold more concurrent slots at equal HBM — and
a pool-exhausted engine preempts the newest stream at a chunk boundary
and resumes it token-exactly later (the router-failover
resume-as-longer-prompt trick). Sliding-window models serve under
paging too: pages that fall wholly out of the attention window are freed
(ring semantics as a page-lifetime policy, no new kernel).

Speculative decoding (``draft_model=`` or ``spec_lookup=``, paged
engines) is universal, not a special case: each tick runs ONE warm
executable that obtains ``spec_tokens`` proposals — a compiled draft
scan over draft KV paged from the SAME pool (separate table columns),
or a host-side prompt-lookup n-gram match with no draft model at all —
then verifies them with one fixed-width ``[1, K+1]`` target forward
against the paged view. Greedy engines accept the longest matching
prefix (streams bit-identical to non-speculative greedy: the verify
logits ARE the dense tick's logits); sampled engines apply the exact
rejection-sampling rule (:func:`generation.speculative_accept`) on the
per-slot rng rows, so the emitted distribution is the dense sampled
law. Adapter rows gather inside the same program (the draft stays
base-weight), mesh slices compile the verify tp-sharded with the draft
replicated, and prefix-cache hits rebuild draft KV via a draft-only
chunk program — all under the same zero-recompile pin.

The ASYNC HOST RUNTIME (``async_ticks=True``, the default) takes the
Python host off the device's critical path. JAX dispatch is
asynchronous: a compiled call returns futures immediately, and chaining
``self._state`` through successive calls fixes device execution order
without the host ever waiting. The run loop exploits this by dispatching
tick N+1 — page coverage, membership mask, admission work and all —
against tick N's still-in-flight state futures, then reconciling N
(materialize tokens, commit, retire) while N+1 runs. The dispatch uses a
SPECULATIVE view of the batch: host state is stale by exactly the one
in-flight tick, so a stream that retires at N wastes one masked lane at
N+1 (its stray token is discarded by an epoch/validity check at
reconcile — emission stays exactly once), streams within one token of
``max_new_tokens`` are conservatively excluded (their stray write would
exceed the position bound), and pages are pre-allocated one position
ahead. Page-table snapshots (``.copy()`` per dispatch) double-buffer the
host tables: reconcile-time frees/preemptions mutate the live table
while the in-flight program reads its own generation, and device program
order guarantees any write a stale snapshot routes into a
since-recycled page happens BEFORE the page's new owner prefills it
(overwrite-before-attend, again). Streaming callbacks move to a bounded
per-request queue drained by an emitter thread, so a slow consumer
flow-controls its own stream (skipped lanes, ``emission_stalls``) and
never stalls the batch; a retiring stream's completion is deferred
behind its buffered callbacks (drain-on-retire barrier). Token streams
are identical to ``async_ticks=False`` across every path — dense,
paged, adapters, mesh slices, speculative — with the same warm
executables; what changes is that ``host_us_per_tick`` (scheduling +
commit wall) hides under device time instead of adding to ITL. One
carve-out: prompt-lookup engines reconcile before dispatching (no
ahead tick) — their proposals anchor on the newest committed token,
and a proposal drafted one variable-length tick behind verifies to
zero accepts, which would trade all of lookup's acceptance for the
overlap.

Around the compiled programs: a bounded FCFS admission queue with
backpressure, per-request ``max_new_tokens``/timeout/cancellation,
streaming token callbacks, error isolation (a failing callback frees its
slot without touching the rest of the batch), and a graceful drain on
shutdown that cooperates with ``Accelerator.install_preemption_handler()``
— on preemption the engine stops admitting, finishes in-flight requests
(including mid-prefill ones), and cancels the queue, so the process can
exit inside the notice window.
"""

from __future__ import annotations

import collections
import hashlib
import itertools
import os
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..adapters.registry import AdapterBank
from ..generation import (
    _bucket128,
    _check_position_bound,
    _chunk_prefill_token,
    _make_selector,
    _make_warper,
    _next_token,
    speculative_emit,
)
from ..inference import resolve_model_source
from ..observability import FlightRecorder, Tracer, new_trace_id
from .metrics import ServingStats
from .request import Request, RequestStatus
from .control import PriorityPolicy
from .scheduler import (
    AdmissionQueue,
    PagePool,
    PrefixCache,
    QueueClosed,
    QueueFull,
    SlotScheduler,
)

__all__ = ["ServingEngine"]

#: distinct tracer/flight-recorder identities per engine in one process.
_ENGINE_SEQ = itertools.count()


class _TickFlight:
    """One dispatched-but-unreconciled decode tick: the (slot, request,
    preemption-epoch) entries the mask was built from, the un-materialized
    device outputs, and the dispatch timestamp. Reconcile commits an
    entry only if its request is still RUNNING *and* its preemption epoch
    matches — a stream retired, failed, or preempted-and-readmitted after
    dispatch must not absorb the stale in-flight token (exactly-once
    emission)."""

    __slots__ = ("entries", "toks", "dones", "emit", "ns", "lookup_hits",
                 "t_dispatch")

    def __init__(self, entries, t_dispatch, toks=None, dones=None,
                 emit=None, ns=None, lookup_hits=0):
        self.entries = entries          # [(slot, req, req._preempted)]
        self.t_dispatch = t_dispatch
        self.toks = toks                # dense/paged tick outputs
        self.dones = dones
        self.emit = emit                # speculative tick outputs
        self.ns = ns
        self.lookup_hits = lookup_hits


class _TokenEmitter:
    """Off-thread ``on_token`` delivery: the engine thread enqueues
    (request, token) pairs — and a ``None``-token finish sentinel AFTER a
    retiring request's last token, the drain-on-retire barrier — and one
    daemon thread drains them in order. A raising callback is recorded on
    the request (``_emit_error``); the engine's loop-top sweep turns that
    into the same FAILED retirement an inline callback failure produces.
    The queue is unbounded here; the ENGINE bounds it per request by
    flow-controlling streams whose ``_emit_pending`` exceeds
    ``max_pending`` (they are skipped from ticks, never stalled on).
    ``close()`` drains everything already queued, then joins — shutdown
    and failover never drop buffered tokens."""

    def __init__(self, max_pending: int):
        self.max_pending = int(max_pending)
        self._q: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._drain_loop,
                                        name="serving-emitter", daemon=True)
        self._thread.start()

    @property
    def alive(self) -> bool:
        return self._thread.is_alive() and not self._closed

    def backlogged(self, req) -> bool:
        """Engine-side flow control: has this stream's consumer fallen
        ``max_pending`` callbacks behind?"""
        return req._emit_pending >= self.max_pending

    def put(self, req, token: int):
        req._emit_pending += 1
        with self._cv:
            self._q.append((req, token))
            self._cv.notify()

    def finish(self, req):
        """Queue the completion sentinel — ``req._complete()`` runs only
        after every callback queued before it has been delivered."""
        with self._cv:
            self._q.append((req, None))
            self._cv.notify()

    def close(self, timeout: Optional[float] = None):
        """Stop accepting work, drain what is queued, join (idempotent)."""
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout)

    def _drain_loop(self):
        while True:
            with self._cv:
                while not self._q and not self._closed:
                    self._cv.wait(0.1)
                if not self._q:
                    return  # closed and fully drained
                batch = list(self._q)
                self._q.clear()
            for req, token in batch:
                if token is None:
                    req._complete()
                    continue
                if req._emit_error is None and req.on_token is not None:
                    try:
                        req.on_token(token)
                    except BaseException as e:
                        # Recorded, not raised: error isolation — the
                        # engine retires THIS request FAILED at its next
                        # sweep; the emitter keeps serving other streams.
                        req._emit_error = e
                req._emit_pending -= 1


class ServingEngine:
    """Slot-based continuous-batching decode service.

    Args:
      model: an accelerate_tpu ``Model``/``AcceleratedModel`` or a bare
        cache-threading flax module (see ``generation.supports_kv_cache``).
      params: parameter pytree (defaults to the prepared model's).
      max_slots: decode lanes — the fixed batch dimension of the tick.
      max_len: per-slot KV capacity; every request must satisfy
        ``prompt_len + max_new_tokens <= max_len``.
      eos_token_id / do_sample / temperature / top_k / top_p: ENGINE-level
        sampling config — baked into the compiled executables (a
        per-request change would be a recompile). Greedy when
        ``do_sample=False``.
      cache_dtype: KV buffer dtype (default bfloat16, like offline).
      kv_dtype: ``"int8"`` stores the paged KV pool quantized — each page
        row is symmetric int8 with one per-page f32 scale held in a
        ``pscale`` state array indexed by page id, written by the same
        executables that write the page (quantize at the page scatter,
        dequantize at the gather into the dense view). Pages cost half
        the bytes, so the same HBM pool admits ~2x the concurrent
        streams; alloc/free/alias/preempt stay pure host work because
        scales live device-side keyed by page id. Requires the paged
        engine. ``None`` (default) keeps the full-precision pool and
        traces byte-identical programs to before this knob existed —
        the bit-exact mode. Exactness under ``"int8"`` is
        bounded-divergence instead: see ``logprob_drift`` in bench and
        docs/usage_guides/serving.md.
      weights_dtype: ``"int8"`` quantizes eligible BASE weight kernels
        per-output-channel (:func:`~accelerate_tpu.adapters.
        quantize_base_weights`); each program dequantizes at its top and
        XLA fuses the ``convert * scale`` into the consuming dots, so
        weights at rest stay int8. The LoRA low-rank path (AdapterBank,
        identity row 0 included) stays full precision — multi-tenant
        adapters apply exactly on the quantized base. ``None`` (default)
        serves full-precision weights.
      max_queued: admission-queue bound (backpressure past it).
      prefill_chunk: width of the single fixed-shape prefill executable
        (clamped to ``max_len`` and the model's position table); a prompt
        of any length runs as identical ``[1, prefill_chunk]`` chunk
        calls. ``None`` selects the legacy monolithic path (one compiled
        prefill per 128-bucketed prompt length, admission runs the whole
        prompt inline) — kept for A/B measurement.
      prefill_chunks_per_tick: admission budget — at most this many chunk
        calls run between consecutive decode ticks, alternating
        continuations of the ``PREFILLING`` backlog (round-robin) with
        new admissions, bounding how much any arrival can delay active
        streams' next token. At the default 1 a new arrival waits for the
        backlog to drain; 2+ lets its first chunk ride alongside an
        in-flight long prefill.
      prefix_cache_mb: LRU budget for chunk-aligned prefix KV blocks
        (0 disables). On admit, the longest cached chunk-aligned prefix
        is restored by ``restore_prefix`` instead of recomputed; the
        final chunk always re-runs so the first token's logits exist.
        Cache keys include the request's adapter identity — two tenants
        with identical prompts never share KV blocks.
      adapters: optional :class:`~accelerate_tpu.adapters.AdapterBank` —
        multi-tenant LoRA serving. The bank rides into every compiled
        program as a regular stacked-array argument and each slot gathers
        its own adapter row inside the forward, so requests naming
        different adapters share one decode batch and adapter load/evict
        (a ``dynamic_update_slice`` row write) compiles nothing new.
        Requests with ``adapter=None`` use bank row 0, the reserved
        identity adapter — their output is the base model's, unchanged.
        In mesh mode the bank is placed onto this engine's slice (see
        :meth:`AdapterBank.place`), so each slice engine needs its OWN
        bank instance.
      tp: tensor-parallel width — carve ``tp`` devices (the first ``tp``
        of ``devices``/``jax.devices()``) into ONE slice and serve this
        engine across it. Mutually consistent with ``mesh=``.
      mesh: an explicit tp-only :class:`jax.sharding.Mesh` (e.g. from
        :meth:`~.mesh_exec.SlicePlan.build_mesh`) for this engine's
        slice. A tp-only mesh resolved from a prepared model/accelerator
        routes here automatically; a mesh with non-trivial dp/fsdp/...
        axes is rejected (see ``_resolve_serving_mesh``).
      devices: with ``tp=``, the device pool to carve the slice from
        (default ``jax.devices()``).
      prefix_cache: a pre-built (possibly fleet-shared)
        :class:`~.scheduler.PrefixCache` to use instead of constructing
        one from ``prefix_cache_mb`` — how ``ReplicaSet.from_mesh``
        gives every slice one cache for cross-slice prefix hits.
      accelerator: optional — wires preemption-drain cooperation and, when
        the accelerator carries a ``serving_stats``, shares it so
        ``Accelerator.log(include_serving=True)`` sees this engine.
      paged: use the paged KV pool instead of dense per-slot rows.
        ``None`` (default) auto-selects paging whenever chunked prefill
        is on; ``False`` keeps the dense layout (the A/B baseline);
        ``True`` with ``prefill_chunk=None`` is an error (pages are
        chunk-granular).
      page_size: tokens per KV page (default = ``prefill_chunk`` so
        PrefixCache blocks map onto whole pages and cache hits restore
        by table ALIASING); must divide ``prefill_chunk``.
      max_pages: usable pool pages (page 0 scratch is extra). Default
        ``max_slots * ceil(max_len / page_size)`` — enough that paging
        can never serve FEWER requests than dense; pass less to
        overcommit memory and lean on preemption.
      draft_model / draft_params: enable speculative decoding — a small
        cache-threading draft module proposing ``spec_tokens`` tokens per
        tick, verified by one fixed-width target forward. Requires
        ``paged=True`` (draft KV pages come from the same pool, so a
        speculative slot costs roughly twice the pages); composes with
        sampling (exact rejection-rule acceptance), adapter banks (the
        target verify gathers the slot's row; the draft runs base
        weights), mesh slices (draft replicated, verify tp-sharded), and
        prefix caches (restored prefixes rebuild draft KV through a
        dedicated draft-only chunk program).
      spec_tokens: draft proposals per speculative tick (default 4).
      spec_lookup: n-gram width for DRAFT-FREE prompt-lookup speculation
        (mutually exclusive with ``draft_model``): each tick proposes
        ``spec_tokens`` tokens by matching the slot's last ``spec_lookup``
        tokens against their most recent earlier occurrence in
        prompt+output (host-side numpy; proposals ride into the verify
        executable as traced data). No draft params, no draft KV, no
        extra pages — the big win for self-repeating RAG/doc traffic.
      tracing: keep the request-scoped span tracer enabled (the default —
        the hot path is a lock-free ring append, guarded ≤5% decode
        overhead). ``False`` turns every emit into an early return; the
        flight recorder stays on either way (its events are rare).
      trace_capacity: spans kept per emitting thread (drop-oldest).
      flight_capacity: structured events the flight recorder retains.
      trace_dir: when set, the engine writes ``<name>-trace.json`` /
        ``<name>-flight.json`` here on shutdown or death (the
        ``accelerate-tpu serve --trace-dir`` plumbing).
      chaos: an optional :class:`~.chaos.ChaosSchedule` of scripted
        faults (kill at decode tick T, hang via heartbeat suppression,
        slow ticks, a wedge inside a dispatched call) applied from the
        run loop — the deterministic fault-injection harness behind the
        self-healing tests.
      async_ticks: run the ASYNC host runtime (default): after
        dispatching tick N the loop immediately schedules pages,
        admission, and tick N+1 against the still-in-flight state
        futures (JAX async dispatch), reconciling N's tokens when they
        materialize — host scheduling/commit work overlaps device
        compute, and per-token streaming callbacks move to a dedicated
        emitter thread so a slow consumer can never stall the tick
        loop. Token streams are identical to sync mode (a stream that
        retires at tick N wastes one masked lane at N+1; the lane's
        extra token is discarded host-side) and the compiled programs
        are byte-identical — ``async_ticks=False`` is the strictly
        tick-synchronous A/B fallback (dispatch, block, commit, inline
        callbacks), the pre-async behavior.
      emission_queue: per-request bound on emitter-queued ``on_token``
        callbacks (async mode only). A stream whose consumer falls this
        far behind is flow-controlled — skipped from decode ticks
        (``emission_stalls`` counts them) until its queue drains —
        instead of growing host memory or stalling the batch.
      autostart: spawn the engine thread (and warm up) in the constructor.
      warmup: run dummy requests through every program at start so the
        first real request never pays a compile; stats, spans, and
        flight events reset afterwards.
    """

    def __init__(self, model, params=None, *, max_slots: int = 4,
                 max_len: int = 256, eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 cache_dtype=None, kv_dtype: Optional[str] = None,
                 weights_dtype: Optional[str] = None, max_queued: int = 64,
                 priority_policy: Optional[PriorityPolicy] = "default",
                 prefill_chunk: Optional[int] = 256,
                 prefill_chunks_per_tick: int = 1,
                 prefix_cache_mb: float = 64.0,
                 adapters: Optional[AdapterBank] = None,
                 paged: Optional[bool] = None,
                 page_size: Optional[int] = None,
                 max_pages: Optional[int] = None,
                 draft_model=None, draft_params=None, spec_tokens: int = 4,
                 spec_lookup: Optional[int] = None,
                 tp: Optional[int] = None, mesh=None, devices=None,
                 prefix_cache: Optional[PrefixCache] = None,
                 accelerator=None, stats: Optional[ServingStats] = None,
                 tracing: bool = True, trace_capacity: int = 4096,
                 flight_capacity: int = 256,
                 trace_dir: Optional[str] = None,
                 chaos=None,
                 async_ticks: Optional[bool] = None,
                 emission_queue: int = 256,
                 autostart: bool = True, warmup: bool = True,
                 idle_poll_s: float = 0.005):
        from ..big_modeling import cache_factory_for

        module, _, params, resolved_mesh, _ = resolve_model_source(
            model, params=params, accelerator=accelerator)
        if params is None:
            raise ValueError("ServingEngine needs params (pass params= or a "
                             "prepared Model)")
        if module is None or hasattr(module, "init_decode_cache"):
            raise NotImplementedError(
                "ServingEngine serves decoder-only cache-threading modules; "
                "encoder-decoder models go through seq2seq_generate")
        factory = cache_factory_for(module)
        if factory is None:
            raise TypeError(
                f"{type(module).__name__} does not thread a KV cache "
                "(big_modeling.cache_factory_for) — the engine cannot hold "
                "its decode state")
        if max_slots < 1 or max_len < 2:
            raise ValueError(f"need max_slots >= 1 and max_len >= 2 "
                             f"(got {max_slots}, {max_len})")
        if prefill_chunk is not None and int(prefill_chunk) < 1:
            raise ValueError(
                f"prefill_chunk must be >= 1 or None (got {prefill_chunk})")
        if prefill_chunks_per_tick < 1:
            raise ValueError("prefill_chunks_per_tick must be >= 1 "
                             f"(got {prefill_chunks_per_tick})")
        if prefix_cache_mb < 0:
            raise ValueError(
                f"prefix_cache_mb must be >= 0 (got {prefix_cache_mb})")

        self.module = module
        self.params = params
        serving_mesh = self._resolve_serving_mesh(tp, mesh, devices,
                                                  resolved_mesh, params)
        #: the engine's slice mesh when mesh-sliced, else whatever mesh the
        #: model source carried (informational, as before).
        self.mesh = serving_mesh if serving_mesh is not None else resolved_mesh
        if serving_mesh is not None:
            from .mesh_exec import SliceExec

            self._exec: Optional["SliceExec"] = SliceExec(serving_mesh)
            if prefill_chunk is None:
                raise NotImplementedError(
                    "the monolithic prefill path (prefill_chunk=None) is "
                    "single-chip only; mesh-sliced engines require chunked "
                    "prefill (pass a prefill_chunk width)")
        else:
            self._exec = None
        #: tensor-parallel width of this engine's slice (1 = single-chip).
        self.tp = self._exec.tp if self._exec is not None else 1
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.eos_token_id = eos_token_id
        self._dtype = cache_dtype or jnp.bfloat16
        self._factory = factory
        self._sampling = (float(temperature), top_k, top_p) if do_sample else None
        self._select = _make_selector(self._sampling)
        self._idle_poll_s = float(idle_poll_s)
        self._accelerator = accelerator

        # The usable position range: max_len capped at the model's learned
        # position table (writing KV at an OOB learned position is not just
        # wasteful — gathers past the table poison the row).
        bound = getattr(getattr(module, "config", None),
                        "max_position_embeddings", None)
        self._chunk_limit = (self.max_len if bound is None
                             else min(self.max_len, int(bound)))
        if prefill_chunk is None:
            self._chunk: Optional[int] = None
            self._chunk_cap = 0
        else:
            self._chunk = min(int(prefill_chunk), self._chunk_limit)
            # The final chunk may start below its natural i*C offset so its
            # fixed width never writes past max_len / the position table
            # (re-running already-prefilled positions rewrites identical KV).
            self._chunk_cap = self._chunk_limit - self._chunk
        self._chunks_per_tick = int(prefill_chunks_per_tick)

        # -- paged-pool resolution (before the prefix cache: an alias-mode
        # cache wires its eviction hook to the page pool) ----------------
        if paged is None:
            paged = self._chunk is not None
        if paged and self._chunk is None:
            raise ValueError(
                "paged=True requires chunked prefill (pages are allocated at "
                "chunk granularity); pass a prefill_chunk width")
        self._paged = bool(paged)
        if self._paged:
            P = int(page_size) if page_size is not None else self._chunk
            if P < 1 or self._chunk % P != 0:
                raise ValueError(
                    f"page_size ({page_size}) must be >= 1 and divide the "
                    f"prefill chunk ({self._chunk}) so chunk writes and "
                    "cached blocks cover whole pages")
            self._page: Optional[int] = P
        else:
            if page_size is not None or max_pages is not None:
                raise ValueError(
                    "page_size=/max_pages= only apply to the paged engine "
                    "(paged=False keeps dense per-slot rows)")
            self._page = None

        # -- quantized serving resolution --------------------------------
        # kv int8 lives at PAGE granularity (one scale per page row), so it
        # needs the paged pool; kv_dtype=None must trace byte-identical
        # programs to the pre-quantization engine — every quant/dequant
        # site below is gated on the scale arrays being present at all.
        if kv_dtype not in (None, "int8"):
            raise ValueError(
                f"kv_dtype must be None or 'int8' (got {kv_dtype!r})")
        if weights_dtype not in (None, "int8"):
            raise ValueError(
                f"weights_dtype must be None or 'int8' (got {weights_dtype!r})")
        if kv_dtype is not None and not self._paged:
            raise ValueError(
                "kv_dtype='int8' requires the paged engine (per-page scales "
                "live in page-id-indexed state); pass paged=True or drop "
                "kv_dtype")
        self._kv_dtype = kv_dtype
        self._weights_dtype = weights_dtype

        # -- speculative-decoding resolution ------------------------------
        # Two drafting modes share one verify program shape: a DRAFT MODEL
        # (paged draft KV alongside the target's) or host-side
        # PROMPT-LOOKUP n-gram proposals (no draft state at all). Either
        # composes with sampling, adapters, mesh slices, and prefix caches
        # — speculation is no longer a special case.
        if draft_model is not None and spec_lookup is not None:
            raise ValueError(
                "draft_model= and spec_lookup= are mutually exclusive — one "
                "engine drafts either with a model or by prompt lookup")
        if draft_model is not None or spec_lookup is not None:
            if not self._paged:
                raise NotImplementedError(
                    "speculative decoding requires the paged engine "
                    "(paged=True)")
            if int(spec_tokens) < 1:
                raise ValueError(
                    f"spec_tokens must be >= 1 (got {spec_tokens})")
            self._spec_k: Optional[int] = int(spec_tokens)
        else:
            self._spec_k = None
        self._spec_lookup: Optional[int] = None
        if draft_model is not None:
            self._spec_mode: Optional[str] = "draft"
            dmod, _, dparams, _, _ = resolve_model_source(
                draft_model, params=draft_params)
            if dparams is None:
                raise ValueError("draft_model needs params (pass "
                                 "draft_params= or a prepared Model)")
            dfactory = cache_factory_for(dmod)
            if dfactory is None:
                raise TypeError(
                    f"{type(dmod).__name__} does not thread a KV cache; it "
                    "cannot draft for the serving engine")
            tv = getattr(getattr(module, "config", None), "vocab_size", None)
            dv = getattr(getattr(dmod, "config", None), "vocab_size", None)
            if tv is not None and dv is not None and tv != dv:
                raise ValueError(
                    f"draft vocab ({dv}) != target vocab ({tv}); acceptance "
                    "compares token ids, so the vocabularies must match")
            self._draft_module, self._draft_params = dmod, dparams
            self._draft_factory = dfactory
        elif spec_lookup is not None:
            if int(spec_lookup) < 1:
                raise ValueError(
                    f"spec_lookup (n-gram width) must be >= 1 "
                    f"(got {spec_lookup})")
            self._spec_mode = "lookup"
            self._spec_lookup = int(spec_lookup)
            self._draft_module = self._draft_params = None
            self._draft_factory = None
        else:
            self._spec_mode = None
            self._draft_module = self._draft_params = None
            self._draft_factory = None
        #: the sampling-target warper, shared with the rejection-sampling
        #: accept rule (sampled speculation must agree with the selector on
        #: the warped distribution EXACTLY).
        self._warp = (_make_warper(self._sampling)
                      if self._sampling is not None else None)
        self._dtable = None          # draft page-table (draft mode only)
        self._draft_page_bytes = 0

        if prefix_cache is not None:
            if self._chunk is None:
                raise ValueError(
                    "prefix_cache= requires chunked prefill "
                    "(prefill_chunk=None has no chunk-aligned blocks)")
            self._prefix_cache: Optional[PrefixCache] = prefix_cache
            self._alias_cache = False   # external/shared cache: COPY restores
        elif self._chunk is not None and prefix_cache_mb > 0:
            # A PRIVATE cache on a paged engine stores page-id tuples, not
            # KV blocks: a hit is a host table write + refcount (aliasing),
            # and eviction gives the pages back through the hook.
            self._alias_cache = self._paged
            self._prefix_cache = PrefixCache(
                int(prefix_cache_mb * 2 ** 20),
                on_evict=self._on_prefix_evict if self._alias_cache else None)
        else:
            self._prefix_cache = None
            self._alias_cache = False
        self._prefilling: collections.deque[Request] = collections.deque()

        # One slot's cache is the state template. Ring (sliding-window)
        # caches rotate by stored position — the dense slot-stacked layout
        # cannot model that, but the PAGED layout serves them: the gathered
        # view is always a full-length LINEAR cache (the model's linear
        # branch applies the window mask), and ring semantics become a
        # page-lifetime policy (out-of-window pages are freed). Only the
        # dense path refuses.
        slot_shape = jax.eval_shape(
            lambda: self._factory(1, self.max_len, self._dtype))
        has_ring = any(isinstance(layer, dict) and "pos" in layer
                       for layer in slot_shape)
        if has_ring and not self._paged:
            raise NotImplementedError(
                "sliding-window (ring) KV caches need the paged engine "
                "(paged=True frees out-of-window pages); the dense slot "
                "layout cannot rotate them — or set the config's window "
                ">= max_len")
        if self._chunk is not None:
            # The paged template probes at tiny lengths where every layer is
            # linear (a window >= 2 never rings at length 2) because the
            # gathered page view is a full-length linear cache; the dense
            # chunked path keeps the max_len probes.
            self._cache_axes = (self._cache_length_axes(2, 1) if self._paged
                                else self._cache_length_axes())
        cfg = getattr(module, "config", None)
        win = getattr(cfg, "sliding_window", None)
        #: window width when pages wholly out of the attention window may be
        #: freed: paged + every layer uniformly windowed (mixed local/global
        #: stacks keep all pages — correctness first, no freeing).
        self._page_window = (
            int(win) if (self._paged and has_ring and isinstance(win, int)
                         and getattr(cfg, "layer_types", None) is None)
            else None)

        if self._paged:
            probe = jax.eval_shape(lambda: self._factory(1, 2, self._dtype))
            self._cache_struct = jax.tree.structure(probe)
            K = self._spec_k or 0
            # The view must hold max_len + K positions: a verify near the
            # end of a stream writes up to pos + K, and the model's internal
            # dynamic_update_slice would CLAMP (corrupting earlier
            # positions) if the view were shorter.
            self._pages_per_slot = -(-(self.max_len + K) // self._page)
            usable = (int(max_pages) if max_pages is not None
                      else self.max_slots * (-(-self.max_len // self._page)))
            if usable < 1:
                raise ValueError(f"max_pages must be >= 1 (got {max_pages})")
            self._pool = PagePool(usable)
            self._table = np.zeros((self.max_slots, self._pages_per_slot),
                                   np.int32)
            quant = self._kv_dtype is not None
            pool_leaves, self._page_bytes = [], 0
            for sh, ax in zip(jax.tree.leaves(probe), self._cache_axes):
                shape = list(sh.shape)
                shape[ax] = self._page
                # +1: page 0 is the reserved scratch page every clamped or
                # inactive write routes to.
                pool_leaves.append(jnp.zeros(
                    (usable + 1,) + tuple(shape),
                    jnp.int8 if quant else sh.dtype))
                # Quantized pages charge 1 byte/element + 4 bytes for the
                # per-page scale — _page_bytes feeds every byte-accounting
                # path (pool metrics, alias-put nbytes, per-chip HBM), so
                # all of them report quantized bytes automatically.
                self._page_bytes += (
                    int(np.prod(shape))
                    * (1 if quant else np.dtype(sh.dtype).itemsize)
                    + (4 if quant else 0))
            self._state = {
                "pool": jax.tree.unflatten(self._cache_struct, pool_leaves),
                "pos": jnp.zeros((self.max_slots,), jnp.int32),
                "tok": jnp.zeros((self.max_slots,), jnp.int32),
                "rng": jnp.zeros((self.max_slots, 2), jnp.uint32),
                "done": jnp.zeros((self.max_slots,), bool),
            }
            if quant:
                # Per-page dequant scales, one row per pool leaf, indexed
                # by page id like the pool itself — device-resident, so a
                # host page-table alias restore (table write + incref)
                # reuses the page's scale with zero device work. Ones keep
                # scratch-page gathers finite before any real write.
                self._state["pscale"] = jnp.ones(
                    (len(pool_leaves), usable + 1), jnp.float32)
            if self._spec_mode == "draft":
                dshape = jax.eval_shape(lambda: self._draft_factory(
                    1, self.max_len + self._spec_k, self._dtype))
                if any(isinstance(layer, dict) and "pos" in layer
                       for layer in dshape):
                    raise NotImplementedError(
                        "the draft model's KV cache must be linear at "
                        "max_len + spec_tokens (raise its sliding window)")
                # Draft KV pages come from the SAME pool as the target's —
                # one id space, one refcount, honest page accounting — but
                # live in their own ``dpool`` leaves (draft layer geometry)
                # behind their own table columns.
                dprobe = jax.eval_shape(
                    lambda: self._draft_factory(1, 2, self._dtype))
                self._draft_cache_struct = jax.tree.structure(dprobe)
                self._draft_cache_axes = self._cache_length_axes(
                    2, 1, factory=self._draft_factory)
                dpool_leaves, self._draft_page_bytes = [], 0
                for sh, ax in zip(jax.tree.leaves(dprobe),
                                  self._draft_cache_axes):
                    shape = list(sh.shape)
                    shape[ax] = self._page
                    dpool_leaves.append(jnp.zeros(
                        (usable + 1,) + tuple(shape),
                        jnp.int8 if quant else sh.dtype))
                    self._draft_page_bytes += (
                        int(np.prod(shape))
                        * (1 if quant else np.dtype(sh.dtype).itemsize)
                        + (4 if quant else 0))
                self._state["dpool"] = jax.tree.unflatten(
                    self._draft_cache_struct, dpool_leaves)
                if quant:
                    self._state["dpscale"] = jnp.ones(
                        (len(dpool_leaves), usable + 1), jnp.float32)
                self._dtable = np.zeros(
                    (self.max_slots, self._pages_per_slot), np.int32)
        else:
            self._pool = None
            self._table = None
            slot_cache = self._factory(1, self.max_len, self._dtype)
            self._state = {
                "cache": jax.tree.map(
                    lambda l: jnp.zeros((self.max_slots,) + l.shape, l.dtype),
                    slot_cache),
                "pos": jnp.zeros((self.max_slots,), jnp.int32),
                "tok": jnp.zeros((self.max_slots,), jnp.int32),
                "rng": jnp.zeros((self.max_slots, 2), jnp.uint32),
                "done": jnp.zeros((self.max_slots,), bool),
            }
        # Adapter bank: the per-slot adapter row index joins the decode
        # state ONLY when a bank is attached — a bank-less engine traces
        # byte-identical programs to the pre-adapter engine.
        self._adapters = adapters
        if adapters is not None:
            self._state["adapter_idx"] = jnp.zeros((self.max_slots,),
                                                   jnp.int32)

        # Base-weight quantization happens ONCE here, before any program is
        # staged: eligible kernels become QuantizedTensor pytree leaves
        # (per-output-channel int8) and every compiled program dequantizes
        # at its top via _dq — XLA fuses convert*scale into the consuming
        # dots, so weights at rest in HBM stay integer. The LoRA bank is
        # untouched: adapter deltas apply full precision on the dequantized
        # base, keeping multi-tenant adapters exact.
        if self._weights_dtype is not None:
            from ..adapters.quantize import quantize_base_weights
            self.params = params = quantize_base_weights(params)

        # CPU jit warns (and ignores) donation; donate only where it works.
        donate = () if jax.default_backend() == "cpu" else (1,)
        # A paged engine with its private alias cache restores prefixes by
        # host page-table writes — there is no compiled restore program at
        # all (steady state is TWO warm executables, not three).
        self._restore_prefix = None
        self._spec = None
        self._draft_chunk = None
        if self._exec is None:
            if self._paged:
                self._decode = jax.jit(self._paged_decode_fn,
                                       donate_argnums=donate)
                self._prefill_chunk = jax.jit(self._paged_prefill_chunk_fn,
                                              donate_argnums=donate)
                if self._prefix_cache is not None and not self._alias_cache:
                    # Only a shared EXTERNAL cache needs the copy-restore
                    # program — the private cache restores by table aliasing
                    # (pure host work, nothing to compile).
                    self._restore_prefix = jax.jit(
                        self._paged_restore_prefix_fn,
                        donate_argnums=(0,) if donate else ())
                if self._spec_mode == "draft":
                    # state is positional arg 2 of the spec program.
                    self._spec = jax.jit(self._spec_fn,
                                         donate_argnums=(2,) if donate else ())
                    if self._prefix_cache is not None:
                        # Prefix restores rebuild draft KV lazily: a
                        # draft-only chunk forward over the restored tokens
                        # (state is its positional arg 1).
                        self._draft_chunk = jax.jit(
                            self._draft_chunk_fn,
                            donate_argnums=(1,) if donate else ())
                elif self._spec_mode == "lookup":
                    # state is positional arg 1 (no draft params argument).
                    self._spec = jax.jit(self._spec_lookup_fn,
                                         donate_argnums=(1,) if donate else ())
            else:
                self._decode = jax.jit(self._decode_fn, donate_argnums=donate)
                if self._chunk is None:
                    self._prefill = jax.jit(self._prefill_fn,
                                            donate_argnums=donate)
                else:
                    self._prefill_chunk = jax.jit(self._prefill_chunk_fn,
                                                  donate_argnums=donate)
                    # restore donates the STATE only (its arg 0) — the block
                    # is a live prefix-cache entry that must survive the copy.
                    self._restore_prefix = jax.jit(
                        self._restore_prefix_fn,
                        donate_argnums=(0,) if donate else ())
        else:
            # Mesh-sliced compilation: derive every placement once, put
            # params/state/bank exactly onto it (jit with explicit
            # in_shardings rejects committed arrays laid out differently),
            # and compile the SAME program functions with those shardings —
            # the engine's call sites don't change at all. The page pool
            # shards exactly like the dense cache (kv-heads axis split, page
            # axis replicated-in-index like the slot axis); the page table,
            # masks, and per-call scalars stay replicated data.
            exec_ = self._exec
            if self._weights_dtype is not None:
                # Quantized leaves shard by their LOGICAL kernel shape: q
                # takes the kernel's Megatron spec, the size-1 amax scale
                # dim replicates. Same treedef as params, so place/jit
                # accept it like any sharding pytree.
                from ..adapters.quantize import shardings_for_quantized
                self._param_sh = shardings_for_quantized(exec_, params)
            else:
                self._param_sh = exec_.param_shardings(params)
            self.params = params = exec_.place(params, self._param_sh)
            if self._paged:
                tmpl = [jax.ShapeDtypeStruct(l.shape[1:], l.dtype)
                        for l in jax.tree.leaves(self._state["pool"])]
                struct = self._cache_struct
            else:
                tmpl = jax.tree.leaves(slot_cache)
                struct = jax.tree.structure(slot_cache)
            self._state_sh = exec_.state_shardings(self._state, tmpl,
                                                   self._cache_axes)
            self._block_sh = exec_.block_shardings(struct, tmpl,
                                                   self._cache_axes)
            self._state = exec_.place(self._state, self._state_sh)
            rep = exec_.replicated
            if self._paged:
                decode_in = [self._param_sh, self._state_sh, rep, rep]
                chunk_in = [self._param_sh, self._state_sh] + [rep] * 6
                restore_in = (self._state_sh, self._block_sh, rep, rep, rep)
                decode_fn = self._paged_decode_fn
                chunk_fn = self._paged_prefill_chunk_fn
                restore_fn = self._paged_restore_prefix_fn
            else:
                decode_in = [self._param_sh, self._state_sh, rep]
                chunk_in = [self._param_sh, self._state_sh] + [rep] * 5
                restore_in = (self._state_sh, self._block_sh, rep, rep, rep)
                decode_fn = self._decode_fn
                chunk_fn = self._prefill_chunk_fn
                restore_fn = self._restore_prefix_fn
            if adapters is not None:
                self._bank_sh = exec_.bank_shardings(adapters)
                adapters.place(self._bank_sh)
                decode_in.append(self._bank_sh)
                chunk_in += [rep, self._bank_sh]
            if self._spec_mode == "draft":
                # Draft params and KV replicate onto every chip of the
                # slice (see SliceExec.state_shardings): the draft scan is
                # collective-free; only the target verify is tp-sharded.
                self._draft_params = jax.device_put(self._draft_params, rep)
                chunk_in += [rep, rep]      # dparams subtree, dpages row
            self._decode = exec_.jit(
                decode_fn, tuple(decode_in),
                (self._state_sh, rep, rep), donate_argnums=donate)
            self._prefill_chunk = exec_.jit(
                chunk_fn, tuple(chunk_in),
                (self._state_sh, rep, self._block_sh), donate_argnums=donate)
            if not (self._paged and self._alias_cache):
                self._restore_prefix = exec_.jit(
                    restore_fn, restore_in,
                    self._state_sh, donate_argnums=(0,) if donate else ())
            if self._spec_mode == "draft":
                spec_in = [self._param_sh, rep, self._state_sh,
                           rep, rep, rep, rep]
                if adapters is not None:
                    spec_in.append(self._bank_sh)
                self._spec = exec_.jit(
                    self._spec_fn, tuple(spec_in), (self._state_sh, rep, rep),
                    donate_argnums=(2,) if donate else ())
                if self._prefix_cache is not None:
                    self._draft_chunk = exec_.jit(
                        self._draft_chunk_fn,
                        (rep, self._state_sh, rep, rep, rep, rep),
                        self._state_sh, donate_argnums=(1,) if donate else ())
            elif self._spec_mode == "lookup":
                spec_in = [self._param_sh, self._state_sh, rep, rep, rep, rep]
                if adapters is not None:
                    spec_in.append(self._bank_sh)
                self._spec = exec_.jit(
                    self._spec_lookup_fn, tuple(spec_in),
                    (self._state_sh, rep, rep),
                    donate_argnums=(1,) if donate else ())

        if stats is None and accelerator is not None:
            stats = getattr(accelerator, "serving_stats", None)
        self._stats = stats if stats is not None else ServingStats()
        if priority_policy == "default":
            priority_policy = PriorityPolicy()
        elif priority_policy is not None and not isinstance(
                priority_policy, PriorityPolicy):
            raise TypeError(
                "priority_policy must be a PriorityPolicy, None (FCFS), or "
                f"the string 'default' (got {priority_policy!r})")
        self._priority_policy = priority_policy
        self._queue = AdmissionQueue(
            max_queued,
            rank_fn=priority_policy.rank if priority_policy is not None
            else None)
        self._slots = SlotScheduler(self.max_slots)

        # Observability: per-engine span tracer + flight recorder (black
        # box). Both are host-only — no device work, no traced arguments —
        # so enabling them cannot change the compiled programs.
        name = f"engine-{next(_ENGINE_SEQ)}"
        self._tracer = Tracer(capacity=int(trace_capacity),
                              enabled=bool(tracing), name=name)
        self._flight = FlightRecorder(capacity=int(flight_capacity),
                                      name=name, tracer=self._tracer)
        self._trace_dir = trace_dir
        self._compile_watcher = None
        self._postmortem: Optional[dict] = None

        self._accepting = False
        self._stop = False          # hard stop: cancel everything, exit now
        self._drain = False         # finish all accepted work, then exit
        self._abort_queue = False   # preemption: finish running, cancel queued
        self._error: Optional[BaseException] = None
        self._fail_injection: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._warmup_on_start = bool(warmup)

        # Liveness + fault-injection hooks (see serving/supervisor.py and
        # serving/chaos.py): the run loop publishes a monotonic heartbeat
        # every iteration so a watchdog can tell a HUNG engine (stalled
        # loop, error still None) from a dead one; a ChaosSchedule, when
        # attached, injects scripted faults keyed on the decode-tick
        # counter. ``_heartbeat_frozen`` is the chaos harness's hang mode:
        # the loop keeps running but stops publishing, which to a watchdog
        # is indistinguishable from a wedged compiled call.
        self._chaos = chaos
        self._loop_iters = 0
        self._decode_ticks = 0
        self._heartbeat = (0, time.monotonic())
        self._heartbeat_frozen = False
        # Async host runtime: one-tick-ahead dispatch + off-thread token
        # emission (see class docstring). ``_wedge_s`` is the chaos
        # harness's dispatched-call wedge: the next reconcile sleeps it
        # off INSIDE the barrier, so the stall is indistinguishable from
        # a compiled call that never returns.
        if async_ticks is None:
            async_ticks = True
        self._async = bool(async_ticks)
        if int(emission_queue) < 1:
            raise ValueError(
                f"emission_queue must be >= 1 (got {emission_queue})")
        self._emission_queue = int(emission_queue)
        self._emitter: Optional[_TokenEmitter] = None
        self._wedge_s = 0.0
        # Host-blocked time (device waits) accumulated since the last
        # reconcile — subtracting it from the device-complete interval
        # is what isolates host_us_per_tick.
        self._blocked_s = 0.0
        self._last_complete_t: Optional[float] = None
        # Next decode tick that emits a tick_profile flight event (the
        # warmup reset re-arms it so a warmed engine still profiles its
        # first real tick instead of waiting out the 128-tick cadence).
        self._next_profile_tick = 1
        # Page-drain samples (wall time, cumulative pool frees) the shed
        # path turns into a pages/s rate; engine-thread writes, any-thread
        # reads of an immutable tuple snapshot.
        self._drain_samples: collections.deque = collections.deque(maxlen=256)
        if autostart:
            self.start()

    @staticmethod
    def _resolve_serving_mesh(tp, mesh, devices, resolved_mesh, params):
        """Decide this engine's slice mesh (None = single-chip path).

        Explicit spellings win: ``mesh=`` is validated tp-only (and
        checked against ``tp=`` if both are given); ``tp=`` carves one
        slice of that width from ``devices``/``jax.devices()``. Otherwise
        a mesh resolved from a prepared model/accelerator routes
        automatically when it is a multi-device tp-only mesh — and when it
        is NOT tp-only but the params are genuinely sharded across
        devices, serving it replicated would silently gather (or crash
        deep in jit with a device-set mismatch), so that raises the clear
        error here instead. A non-tp training mesh over host-resident
        params (e.g. a default dp accelerator whose params were never
        prepared) keeps the single-chip path: nothing is sharded, so
        nothing is gathered.
        """
        from .mesh_exec import SlicePlan, validate_serving_mesh

        if mesh is not None:
            validate_serving_mesh(mesh)
            if tp is not None and int(mesh.shape["tp"]) != int(tp):
                raise ValueError(
                    f"mesh= has tp={mesh.shape['tp']} but tp={tp} was also "
                    "passed; drop one or make them agree")
            return mesh
        if tp is not None:
            return SlicePlan.plan(int(tp), num_slices=1,
                                  devices=devices).build_mesh(0)
        if devices is not None:
            raise ValueError("devices= only makes sense together with tp=")
        if resolved_mesh is None or resolved_mesh.devices.size <= 1:
            return None
        import math

        non_tp = math.prod(s for ax, s in resolved_mesh.shape.items()
                           if ax != "tp")
        if non_tp == 1 and resolved_mesh.shape.get("tp", 1) > 1:
            return resolved_mesh  # tp-only training mesh: serve sliced
        spanned = set()
        for leaf in jax.tree.leaves(params):
            sharding = getattr(leaf, "sharding", None)
            device_set = getattr(sharding, "device_set", None)
            if device_set:
                spanned |= set(device_set)
        if len(spanned) > 1:
            raise ValueError(
                "params are sharded across "
                f"{len(spanned)} devices on a non-tensor-parallel mesh "
                f"({dict(resolved_mesh.shape)}); the serving engine only "
                "runs tp-only slices. Re-prepare the model under "
                "MeshConfig(dp=1, tp=N), pass tp=/mesh= explicitly, or "
                "gather params to host before serving.")
        return None

    def _cache_length_axes(self, la: Optional[int] = None,
                           lb: Optional[int] = None,
                           factory=None) -> list[int]:
        """Per-leaf sequence-length axis of the slot cache, detected by
        comparing ``eval_shape`` of the factory at two lengths (layouts are
        family-specific; llama is ``[1, L, n_kv, head]`` but nothing
        guarantees that elsewhere). Default probes are ``max_len`` vs
        ``max_len - 1``, never ``+ 1`` — growing past ``max_len`` could
        flip a sliding-window layer into its ring layout and change the
        tree structure itself; the PAGED engine probes at (2, 1) instead,
        where a windowed layer is still linear, because its page template
        must be the linear layout regardless of the window. Flattened-leaf
        order, the same order every tree op in the programs uses."""
        la = self.max_len if la is None else la
        lb = self.max_len - 1 if lb is None else lb
        factory = self._factory if factory is None else factory
        a = jax.tree.leaves(jax.eval_shape(
            lambda: factory(1, la, self._dtype)))
        b = jax.tree.leaves(jax.eval_shape(
            lambda: factory(1, lb, self._dtype)))
        if len(a) != len(b):
            raise NotImplementedError(
                "the KV cache changes structure between probe lengths "
                f"({la} vs {lb}); this layout cannot be paged/chunked")
        axes = []
        for x, y in zip(a, b):
            diff = [i for i, (m, n) in enumerate(zip(x.shape, y.shape))
                    if m != n]
            if len(diff) != 1:
                raise NotImplementedError(
                    "chunked prefill needs every KV leaf to carry exactly "
                    f"one length axis (leaf {x.shape} vs {y.shape} at "
                    f"probe lengths {la}/{lb}); pass prefill_chunk=None "
                    "for the monolithic path")
            axes.append(diff[0])
        return axes

    # ------------------------------------------------------------------
    # the compiled programs
    # ------------------------------------------------------------------
    @staticmethod
    def _lora_kwargs(bank, aidx) -> dict:
        """Gather one adapter row from the stacked bank at a traced index.

        Returns the ``lora=`` kwargs for ``module.apply`` — empty when no
        bank is attached, so bank-less engines never pass the kwarg (and
        non-LoRA-aware modules never see it)."""
        if bank is None:
            return {}
        return {"lora": jax.tree.map(lambda s: s[aidx], bank)}

    def _prefill_fn(self, params, state, ids_p, slot, rng, true_len,
                    aidx=None, bank=None):
        """Monolithic prefill (``prefill_chunk=None`` only). ids_p [1, P]
        edge-padded prompt; slot/true_len traced i32 scalars. Builds a
        fresh batch-1 cache, runs the whole prompt, selects the first
        token exactly like offline generate (the shared
        :func:`generation._chunk_prefill_token` epilogue at offset 0), and
        writes the slot's whole decode state at the traced slot index.
        Returns (state, first_token). One executable per 128-bucketed
        prompt length — the compile-family the chunked path replaces.
        """
        params = self._dq(params)
        cache = self._factory(1, self.max_len, self._dtype)
        logits, cache = self.module.apply(
            {"params": params}, ids_p, cache=cache, cache_pos=0,
            **self._lora_kwargs(bank, aidx))
        tok, done, rng_carry = _chunk_prefill_token(
            logits, rng, self._select, self.eos_token_id, ids_p.dtype,
            true_len)
        new_cache = jax.tree.map(
            lambda full, one: jax.lax.dynamic_update_slice(
                full, one[None].astype(full.dtype), (slot,) + (0,) * one.ndim),
            state["cache"], cache)
        new_state = dict(
            state,
            cache=new_cache,
            pos=state["pos"].at[slot].set(true_len),
            tok=state["tok"].at[slot].set(tok[0].astype(jnp.int32)),
            rng=state["rng"].at[slot].set(rng_carry),
            done=state["done"].at[slot].set(done[0]),
        )
        if bank is not None:
            new_state["adapter_idx"] = state["adapter_idx"].at[slot].set(aidx)
        return new_state, tok[0]

    def _prefill_chunk_fn(self, params, state, ids_c, slot, offset, true_len,
                          rng, aidx=None, bank=None):
        """ONE chunk of prefill: ids_c ``[1, C]`` (tail chunks edge-padded
        on the host); slot/offset/true_len traced i32 scalars. Runs the
        chunk at ``cache_pos=offset`` directly against the slot's region
        of the shared cache (in-place: garbage left by a previous occupant
        is masked-out by construction, see the module docstring), selects
        a candidate first token via the shared epilogue (real only in the
        chunk containing ``true_len - 1``), and writes the slot rows —
        ``pos[slot] = true_len`` on EVERY call, the invariant that keeps
        interleaved decode ticks from corrupting a mid-prefill slot.

        Also returns the chunk's own KV block (each leaf sliced to width C
        on its length axis) so the prefix cache is fed by THIS executable
        — no separate extract program, keeping the steady state at exactly
        one chunk-prefill executable. Returns (state, first_token, block).
        """
        params = self._dq(params)
        C = ids_c.shape[1]
        cache = jax.tree.map(
            lambda full: jax.lax.dynamic_slice(
                full, (slot,) + (0,) * (full.ndim - 1),
                (1,) + full.shape[1:])[0],
            state["cache"])
        logits, cache = self.module.apply(
            {"params": params}, ids_c, cache=cache, cache_pos=offset,
            **self._lora_kwargs(bank, aidx))
        tok, done, rng_carry = _chunk_prefill_token(
            logits, rng, self._select, self.eos_token_id, ids_c.dtype,
            true_len, offset)
        leaves = jax.tree.leaves(cache)
        block = jax.tree.unflatten(
            jax.tree.structure(cache),
            [jax.lax.dynamic_slice_in_dim(l, offset, C, axis=ax)
             for l, ax in zip(leaves, self._cache_axes)])
        new_cache = jax.tree.map(
            lambda full, one: jax.lax.dynamic_update_slice(
                full, one[None].astype(full.dtype), (slot,) + (0,) * one.ndim),
            state["cache"], cache)
        new_state = dict(
            state,
            cache=new_cache,
            pos=state["pos"].at[slot].set(true_len),
            tok=state["tok"].at[slot].set(tok[0].astype(jnp.int32)),
            rng=state["rng"].at[slot].set(rng_carry),
            done=state["done"].at[slot].set(done[0]),
        )
        if bank is not None:
            new_state["adapter_idx"] = state["adapter_idx"].at[slot].set(aidx)
        return new_state, tok[0], block

    def _restore_prefix_fn(self, state, block, slot, offset, true_len):
        """Copy one cached ``[1, C]`` KV block into the slot's cache at the
        traced chunk offset and stamp ``pos[slot] = true_len`` (the same
        decode-tick-safety invariant as the chunk program). The block is
        NOT donated — it stays live in the prefix cache."""
        full_leaves = jax.tree.leaves(state["cache"])
        blk_leaves = jax.tree.leaves(block)
        out = []
        for full, blk, ax in zip(full_leaves, blk_leaves, self._cache_axes):
            start = [0] * full.ndim
            start[0] = slot
            start[ax + 1] = offset
            out.append(jax.lax.dynamic_update_slice(
                full, blk[None].astype(full.dtype), tuple(start)))
        return dict(
            state,
            cache=jax.tree.unflatten(jax.tree.structure(state["cache"]), out),
            pos=state["pos"].at[slot].set(true_len),
        )

    def _decode_fn(self, params, state, active, bank=None):
        """One tick: a batch-1 single-token forward vmapped over the slot
        axis (per-slot scalar cache_pos, per-slot rng chain — bitwise the
        same selection as offline's scan body). The cache commits
        unconditionally — an inactive or PREFILLING slot rewrites its
        ``pos`` with garbage — which is safe because prefill/restore pin
        every mid-prefill slot's pos to ``true_len``, a position no prompt
        chunk reads and the first real decode write overwrites (a retired
        slot's next use starts with a fresh prefill of its region). But
        pos/tok/rng/done advance only where ``active`` is set, so
        non-running slots stay frozen and in-bounds. Returns
        (state, tokens [S], done [S])."""
        params = self._dq(params)

        def one_slot(cache, tok, pos, rng, done, aidx=None):
            logits, cache = self.module.apply(
                {"params": params}, tok[None, None], cache=cache, cache_pos=pos,
                **self._lora_kwargs(bank, aidx))
            rng, sub = jax.random.split(rng)
            nxt, done = _next_token(logits[:, -1], sub, jnp.zeros((1, 1), bool),
                                    done[None], self._select, self.eos_token_id,
                                    tok.dtype)
            return cache, nxt[0], rng, done[0]

        # The bank is closed over (broadcast): each slot gathers its own
        # adapter row at its vmapped adapter_idx.
        vmap_args = [state["cache"], state["tok"], state["pos"], state["rng"],
                     state["done"]]
        if bank is not None:
            vmap_args.append(state["adapter_idx"])
        new_cache, toks, rngs, dones = jax.vmap(one_slot)(*vmap_args)
        state = dict(
            state,
            cache=new_cache,
            pos=jnp.where(active, state["pos"] + 1, state["pos"]),
            tok=jnp.where(active, toks, state["tok"]),
            rng=jnp.where(active[:, None], rngs, state["rng"]),
            done=jnp.where(active, dones, state["done"]),
        )
        return state, toks, dones

    # -- paged programs -------------------------------------------------
    def _dq(self, params):
        """Dequantize int8 base weights at the top of a compiled program.

        Identity when ``weights_dtype`` is None, so full-precision engines
        trace byte-identical programs. XLA fuses the ``convert * scale``
        into each consuming dot — weights at rest in HBM stay int8."""
        if self._weights_dtype is None:
            return params
        from ..adapters.quantize import dequantize_params
        return dequantize_params(params, self._dtype)

    def _quant_page(self, pb):
        """Quantize ONE page block to (int8 page, f32 scale scalar):
        symmetric absmax over the whole page — one scale per page row is
        the whole point, it rides the page id through host alias/free/
        preempt bookkeeping with zero extra device work. The 1e-6 floor
        keeps an all-zero page's dequant finite; round-trip is idempotent
        (q*s re-quantizes to the same q), so external-cache restores that
        re-quantize a dequantized block are stable."""
        f = pb.astype(jnp.float32)
        amax = jnp.max(jnp.abs(f))
        s = jnp.maximum(amax, 1e-6) / 127.0
        q = jnp.clip(jnp.round(f / s), -127, 127).astype(jnp.int8)
        return q, s

    def _gather_view(self, pool, pages, axes=None, struct=None, scales=None):
        """One slot's dense cache VIEW from the pool: gather its page rows
        (``pages`` [Np] i32 pool ids, 0 = scratch for unallocated entries)
        and merge the page axis into the length axis — each leaf becomes
        ``[1, Np * P, ...]``, exactly the linear cache the unchanged
        forward expects. Scratch garbage sits at positions the attention
        mask (causal and/or sliding-window) already excludes. ``axes`` /
        ``struct`` default to the TARGET cache geometry; speculative
        engines pass the draft pool's. ``scales`` (the pool's per-page
        scale array, rows aligned with the pool leaves) dequantizes int8
        page rows in the same gather — None on fp engines."""
        axes = self._cache_axes if axes is None else axes
        struct = self._cache_struct if struct is None else struct
        leaves = []
        for i, (l, ax) in enumerate(zip(jax.tree.leaves(pool), axes)):
            rows = l[pages]
            if scales is not None:
                s = scales[i][pages].reshape((-1,) + (1,) * (rows.ndim - 1))
                rows = (rows.astype(jnp.float32) * s).astype(self._dtype)
            g = jnp.moveaxis(rows, 0, ax)
            shape = (list(g.shape[:ax]) + [g.shape[ax] * g.shape[ax + 1]]
                     + list(g.shape[ax + 2:]))
            leaves.append(g.reshape(shape))
        return jax.tree.unflatten(struct, leaves)

    def _scatter_page(self, pool_leaves, view_leaves, src_page, tgt,
                      axes=None, scales=None):
        """Write view page ``src_page`` back into pool page ``tgt`` (both
        traced i32). ``tgt = 0`` discards into scratch; an out-of-range
        ``src_page`` clamps to the view's last page (jax dynamic_slice
        semantics), which callers pair with a scratch target — the two
        clamps together are what let a FIXED number of scatter steps cover
        a variable number of genuinely-written pages. With ``scales``
        the fp page block quantizes to int8 on the way in and its scale
        lands at ``scales[leaf, tgt]`` (scratch writes overwrite row 0,
        harmlessly). Returns ``(pool_leaves, scales)``."""
        axes = self._cache_axes if axes is None else axes
        out = []
        for i, (pl, vl, ax) in enumerate(zip(pool_leaves, view_leaves, axes)):
            start = [0] * vl.ndim
            start[ax] = src_page * self._page
            sizes = list(vl.shape)
            sizes[ax] = self._page
            pb = jax.lax.dynamic_slice(vl, tuple(start), tuple(sizes))
            if scales is not None:
                pb, s = self._quant_page(pb)
                scales = jax.lax.dynamic_update_slice(
                    scales, s.reshape(1, 1), (i, tgt))
            out.append(jax.lax.dynamic_update_slice(
                pl, pb[None].astype(pl.dtype), (tgt,) + (0,) * pb.ndim))
        return out, scales

    def _scatter_chunk_pages(self, pool_leaves, view_leaves, axes, pages,
                             offset, C, scales=None):
        """Scatter a chunk's writes (positions ``[offset, offset + C)``)
        back into the pool: at most ``C/P + 1`` pages (the pulled-back
        final chunk may start mid-page); the possibly-untouched trailing
        step routes to scratch. Returns ``(pool_leaves, scales)``."""
        p0 = offset // self._page
        for pg in range(C // self._page + 1):
            tid = jax.lax.dynamic_slice(pages, (p0 + pg,), (1,))[0]
            touched = (p0 + pg) * self._page < offset + C
            pool_leaves, scales = self._scatter_page(
                pool_leaves, view_leaves, p0 + pg,
                jnp.where(touched, tid, 0), axes, scales)
        return pool_leaves, scales

    def _paged_prefill_chunk_fn(self, params, state, ids_c, slot, pages,
                                offset, true_len, rng, *extra):
        """Paged twin of :meth:`_prefill_chunk_fn`: gather the slot's pages
        into a dense view, run the chunk at ``cache_pos=offset`` exactly as
        the dense program does, then scatter back only the pages the chunk
        wrote. The returned block is sliced from the view — same bytes as
        the dense block, so external prefix caches stay layout-compatible.

        ``extra`` is positional (mesh in_shardings forbid kwargs) and holds
        whatever this engine's config adds, in order: ``aidx, bank`` when
        an adapter bank is attached, then ``dparams, dpages`` when a draft
        model speculates — the SAME call also prefills the slot's paged
        draft KV, keeping the warm-executable count unchanged."""
        extra = list(extra)
        aidx = bank = None
        if self._adapters is not None:
            aidx, bank = extra[0], extra[1]
            del extra[:2]
        dparams = dpages = None
        if self._spec_mode == "draft":
            dparams, dpages = extra
        params = self._dq(params)
        C = ids_c.shape[1]
        # Per-page scale arrays ride the state dict only on int8 engines —
        # state.get() is None otherwise and every quant/dequant site below
        # vanishes, leaving the fp program byte-identical.
        scales = state.get("pscale")
        view = self._gather_view(state["pool"], pages, scales=scales)
        logits, view = self.module.apply(
            {"params": params}, ids_c, cache=view, cache_pos=offset,
            **self._lora_kwargs(bank, aidx))
        tok, done, rng_carry = _chunk_prefill_token(
            logits, rng, self._select, self.eos_token_id, ids_c.dtype,
            true_len, offset)
        view_leaves = jax.tree.leaves(view)
        # The block is sliced from the DEQUANTIZED view — full precision,
        # so external prefix caches stay layout-compatible across engines
        # (restore re-quantizes; the round-trip is idempotent).
        block = jax.tree.unflatten(
            self._cache_struct,
            [jax.lax.dynamic_slice_in_dim(l, offset, C, axis=ax)
             for l, ax in zip(view_leaves, self._cache_axes)])
        pool_leaves, scales = self._scatter_chunk_pages(
            jax.tree.leaves(state["pool"]), view_leaves, self._cache_axes,
            pages, offset, C, scales)
        new_state = dict(
            state,
            pool=jax.tree.unflatten(self._cache_struct, pool_leaves),
            pos=state["pos"].at[slot].set(true_len),
            tok=state["tok"].at[slot].set(tok[0].astype(jnp.int32)),
            rng=state["rng"].at[slot].set(rng_carry),
            done=state["done"].at[slot].set(done[0]),
        )
        if scales is not None:
            new_state["pscale"] = scales
        if bank is not None:
            new_state["adapter_idx"] = state["adapter_idx"].at[slot].set(aidx)
        if dparams is not None:
            # The draft stays base-weight even under an adapter bank: its
            # proposals only steer acceptance, never the emitted law.
            dscales = state.get("dpscale")
            dview = self._gather_view(state["dpool"], dpages,
                                      self._draft_cache_axes,
                                      self._draft_cache_struct, dscales)
            _, dview = self._draft_module.apply(
                {"params": dparams}, ids_c, cache=dview, cache_pos=offset)
            dpool_leaves, dscales = self._scatter_chunk_pages(
                jax.tree.leaves(state["dpool"]), jax.tree.leaves(dview),
                self._draft_cache_axes, dpages, offset, C, dscales)
            new_state["dpool"] = jax.tree.unflatten(
                self._draft_cache_struct, dpool_leaves)
            if dscales is not None:
                new_state["dpscale"] = dscales
        return new_state, tok[0], block

    def _draft_chunk_fn(self, dparams, state, ids_c, slot, dpages, offset):
        """Draft-only chunk forward: rebuild a prefix-restored slot's draft
        KV for one already-committed chunk (the restored target pages carry
        no draft KV). Runs the cheap draft model only — the target's
        prefix-cache FLOP savings survive — and scatters the chunk's draft
        pages exactly like the fused prefill. Compiled (and warmed) only on
        draft-mode speculative engines with a prefix cache attached."""
        del slot  # symmetry with the fused chunk program's signature
        C = ids_c.shape[1]
        dscales = state.get("dpscale")
        dview = self._gather_view(state["dpool"], dpages,
                                  self._draft_cache_axes,
                                  self._draft_cache_struct, dscales)
        _, dview = self._draft_module.apply(
            {"params": dparams}, ids_c, cache=dview, cache_pos=offset)
        dpool_leaves, dscales = self._scatter_chunk_pages(
            jax.tree.leaves(state["dpool"]), jax.tree.leaves(dview),
            self._draft_cache_axes, dpages, offset, C, dscales)
        out = dict(state, dpool=jax.tree.unflatten(
            self._draft_cache_struct, dpool_leaves))
        if dscales is not None:
            out["dpscale"] = dscales
        return out

    def _paged_restore_prefix_fn(self, state, block, pages_c, slot, true_len):
        """Copy-restore for paged engines with an EXTERNAL (fleet-shared)
        prefix cache: split one cached ``[1, C]`` block into ``C/P`` pages
        and write each into the pool page named by ``pages_c`` (traced
        [C/P] i32 — the slot's freshly-allocated table entries). Pins
        ``pos[slot] = true_len`` like every restore. The engine's PRIVATE
        cache never calls this — it restores by host table aliasing."""
        pool_leaves = jax.tree.leaves(state["pool"])
        scales = state.get("pscale")
        out = []
        for i, (pl, blk, ax) in enumerate(zip(pool_leaves,
                                              jax.tree.leaves(block),
                                              self._cache_axes)):
            Cp = blk.shape[ax] // self._page
            shape = list(blk.shape)
            shape[ax:ax + 1] = [Cp, self._page]
            pages_blk = jnp.moveaxis(blk.reshape(shape), ax, 0)
            for j in range(Cp):
                pb = pages_blk[j]
                if scales is not None:
                    # Cached blocks are fp; re-quantize on restore (the
                    # round-trip is idempotent, so restored pages dequant
                    # to the same values the producing engine attended).
                    pb, s = self._quant_page(pb)
                    scales = jax.lax.dynamic_update_slice(
                        scales, s.reshape(1, 1), (i, pages_c[j]))
                pl = jax.lax.dynamic_update_slice(
                    pl, pb[None].astype(pl.dtype),
                    (pages_c[j],) + (0,) * pb.ndim)
            out.append(pl)
        new_state = dict(
            state,
            pool=jax.tree.unflatten(self._cache_struct, out),
            pos=state["pos"].at[slot].set(true_len),
        )
        if scales is not None:
            new_state["pscale"] = scales
        return new_state

    def _gather_views_all_slots(self, pool, table, axes=None, struct=None,
                                scales=None):
        """Batched :meth:`_gather_view`: ``table`` [S, Np] → per-leaf
        ``[S, 1, Np*P, ...]`` dense views, slot axis leading so the decode
        vmap runs over it unchanged. ``axes``/``struct`` default to the
        target cache geometry (the draft pool passes its own); ``scales``
        dequantizes int8 page rows in the same gather."""
        axes = self._cache_axes if axes is None else axes
        struct = self._cache_struct if struct is None else struct
        leaves = []
        for i, (l, ax) in enumerate(zip(jax.tree.leaves(pool), axes)):
            rows = l[table]
            if scales is not None:
                s = scales[i][table].reshape(
                    table.shape + (1,) * (rows.ndim - 2))
                rows = (rows.astype(jnp.float32) * s).astype(self._dtype)
            g = jnp.moveaxis(rows, 1, ax + 1)
            shape = (list(g.shape[:ax + 1])
                     + [g.shape[ax + 1] * g.shape[ax + 2]]
                     + list(g.shape[ax + 3:]))
            leaves.append(g.reshape(shape))
        return jax.tree.unflatten(struct, leaves)

    def _scatter_slot_pages(self, pool_leaves, nv_leaves, axes, table,
                            active, pos, last_off, steps, scales=None):
        """Scatter every slot's speculative writes back into the pool: the
        pages covering positions ``pos[s] .. pos[s] + last_off``, in a
        FIXED ``steps`` scatter steps per slot. Steps past the touched
        range, and every step of an inactive slot, route to scratch (page
        0) — the same clamp pairing as :meth:`_scatter_page`. Returns
        ``(pool_leaves, scales)``."""
        P = self._page
        for s in range(self.max_slots):
            p0 = pos[s] // P
            for pg in range(steps):
                tid = jax.lax.dynamic_slice(table[s], (p0 + pg,), (1,))[0]
                touched = (p0 + pg) * P <= pos[s] + last_off
                tgt = jnp.where(active[s] & touched, tid, 0)
                new_pool = []
                for i, (pl, vl, ax) in enumerate(zip(pool_leaves, nv_leaves,
                                                     axes)):
                    start = [0] * vl.ndim
                    start[0] = s
                    start[ax + 1] = (p0 + pg) * P
                    sizes = list(vl.shape)
                    sizes[0] = 1
                    sizes[ax + 1] = P
                    pb = jax.lax.dynamic_slice(vl, tuple(start),
                                               tuple(sizes))[0]
                    if scales is not None:
                        pb, sc = self._quant_page(pb)
                        scales = jax.lax.dynamic_update_slice(
                            scales, sc.reshape(1, 1), (i, tgt))
                    new_pool.append(jax.lax.dynamic_update_slice(
                        pl, pb[None].astype(pl.dtype),
                        (tgt,) + (0,) * pb.ndim))
                pool_leaves = new_pool
        return pool_leaves, scales

    def _paged_decode_fn(self, params, state, active, table, bank=None):
        """Paged twin of :meth:`_decode_fn`: gather every slot's view, run
        the identical vmapped batch-1 forward (same logits, same
        :func:`generation._next_token` — paged streams are bit-identical
        to dense), then scatter back ONE page per slot: the page holding
        ``pos[slot]``, the only position a tick writes. Inactive slots
        scatter to scratch, so their stale ``pos`` can't corrupt the pool
        — the paged analogue of the dense path's unconditional-commit
        safety. The host guarantees an active slot's ``pos`` page is
        allocated before every tick."""
        P = self._page
        params = self._dq(params)
        scales = state.get("pscale")
        views = self._gather_views_all_slots(state["pool"], table,
                                             scales=scales)

        def one_slot(cache, tok, pos, rng, done, aidx=None):
            logits, cache = self.module.apply(
                {"params": params}, tok[None, None], cache=cache,
                cache_pos=pos, **self._lora_kwargs(bank, aidx))
            rng, sub = jax.random.split(rng)
            nxt, done = _next_token(logits[:, -1], sub, jnp.zeros((1, 1), bool),
                                    done[None], self._select, self.eos_token_id,
                                    tok.dtype)
            return cache, nxt[0], rng, done[0]

        vmap_args = [views, state["tok"], state["pos"], state["rng"],
                     state["done"]]
        if bank is not None:
            vmap_args.append(state["adapter_idx"])
        new_views, toks, rngs, dones = jax.vmap(one_slot)(*vmap_args)
        nv_leaves = jax.tree.leaves(new_views)
        pool_leaves = jax.tree.leaves(state["pool"])
        for s in range(self.max_slots):
            pg = state["pos"][s] // P
            tid = jax.lax.dynamic_slice(table[s], (pg,), (1,))[0]
            tgt = jnp.where(active[s], tid, 0)
            new_pool = []
            for i, (pl, vl, ax) in enumerate(zip(pool_leaves, nv_leaves,
                                                 self._cache_axes)):
                start = [0] * vl.ndim
                start[0] = s
                start[ax + 1] = pg * P
                sizes = list(vl.shape)
                sizes[0] = 1
                sizes[ax + 1] = P
                pb = jax.lax.dynamic_slice(vl, tuple(start), tuple(sizes))[0]
                if scales is not None:
                    pb, sc = self._quant_page(pb)
                    scales = jax.lax.dynamic_update_slice(
                        scales, sc.reshape(1, 1), (i, tgt))
                new_pool.append(jax.lax.dynamic_update_slice(
                    pl, pb[None].astype(pl.dtype), (tgt,) + (0,) * pb.ndim))
            pool_leaves = new_pool
        state = dict(
            state,
            pool=jax.tree.unflatten(self._cache_struct, pool_leaves),
            pos=jnp.where(active, state["pos"] + 1, state["pos"]),
            tok=jnp.where(active, toks, state["tok"]),
            rng=jnp.where(active[:, None], rngs, state["rng"]),
            done=jnp.where(active, dones, state["done"]),
        )
        if scales is not None:
            state["pscale"] = scales
        return state, toks, dones

    def _spec_accept(self, logits, drafts, done, rem, rng):
        """Per-slot accept epilogue shared by BOTH speculative programs
        (draft-model and prompt-lookup): run the factored accept rule
        (:func:`generation.speculative_emit` — greedy longest-matching-
        prefix, or the exact rejection-sampling rule when this engine
        samples) and derive the slot's committed count, carry token, and
        eos latch. Greedy engines pass the rng through UNTOUCHED (greedy
        selection never consumes it — spec streams stay bit-comparable to
        dense greedy ones); sampled engines split it once per tick, so a
        slot's rng trajectory is one split per verify, mirroring one split
        per dense tick."""
        K = drafts.shape[0]
        if self._sampling is not None:
            rng, step_rng = jax.random.split(rng)
        else:
            step_rng = rng  # unused by the greedy rule
        m, emit = speculative_emit(logits, drafts, step_rng, self._warp,
                                   self.eos_token_id, drafts.dtype,
                                   prior_done=done)
        n = jnp.minimum(m + 1, rem)
        new_tok = emit[jnp.clip(n - 1, 0, K)]
        if self.eos_token_id is not None:
            new_done = new_tok == jnp.asarray(self.eos_token_id,
                                              drafts.dtype)
        else:
            new_done = done
        return emit, n, new_tok, new_done, rng

    def _spec_fn(self, params, dparams, state, active, table, dtable,
                 remaining, bank=None):
        """One SPECULATIVE tick, draft-model mode: per slot, scan K draft
        steps through the slot's PAGED draft view (drafts are the argmax
        of the warped draft logits — a delta proposal, so the sampled
        accept rule stays exact), verify draft + carry token in ONE fixed
        ``[1, K+1]`` target forward against the paged target view (the
        slot's adapter row gathered inside, like the dense tick), and
        accept via :meth:`_spec_accept`. Committing the emitted chain's
        first ``n = min(accepted + 1, remaining)`` tokens is
        token-identical (greedy) / distribution-exact (sampled) to ``n``
        dense ticks.

        Rejected-draft KV (positions past ``pos + n - 1``) is garbage in
        BOTH pools, but the next verify rewrites target positions
        ``pos+n .. pos+n+K`` and the next draft scan rewrites draft
        positions ``pos+n .. pos+n+K-1`` before any query can attend them
        — the same overwrite-before-attend argument the chunked prefill
        pad relies on. Returns ``(state, emitted [S, K+1], n [S])``."""
        P, K = self._page, self._spec_k
        params = self._dq(params)
        scales = state.get("pscale")
        dscales = state.get("dpscale")
        views = self._gather_views_all_slots(state["pool"], table,
                                             scales=scales)
        dviews = self._gather_views_all_slots(
            state["dpool"], dtable, self._draft_cache_axes,
            self._draft_cache_struct, dscales)

        def one_slot(view, dview, tok, pos, done, rem, rng, aidx=None):
            def dstep(carry, _):
                dc, cur, p = carry
                dlog, dc = self._draft_module.apply(
                    {"params": dparams}, cur[None, None], cache=dc,
                    cache_pos=p)
                row = dlog[0, -1][None]
                if self._warp is not None:
                    row = self._warp(row)
                nxt = jnp.argmax(row[0], axis=-1).astype(tok.dtype)
                return (dc, nxt, p + 1), nxt
            (dview, _, _), drafts = jax.lax.scan(
                dstep, (dview, tok, pos), None, length=K)
            ids_v = jnp.concatenate([tok[None], drafts])[None]
            logits, view = self.module.apply(
                {"params": params}, ids_v, cache=view, cache_pos=pos,
                **self._lora_kwargs(bank, aidx))
            emit, n, new_tok, new_done, rng = self._spec_accept(
                logits[0], drafts, done, rem, rng)
            return view, dview, new_tok, n, emit, new_done, rng

        vmap_args = [views, dviews, state["tok"], state["pos"],
                     state["done"], remaining, state["rng"]]
        if bank is not None:
            vmap_args.append(state["adapter_idx"])
        (new_views, new_dviews, toks, ns, emit, dones,
         rngs) = jax.vmap(one_slot)(*vmap_args)
        # A verify writes target positions pos .. pos+K (K//P + 2 scatter
        # steps); the draft scan writes draft positions pos .. pos+K-1.
        # Pages past the slot's allocated frontier (table entry 0, or an
        # untouched trailing step) land in scratch; their positions are
        # rewritten by the next verify before anything attends them.
        pool_leaves, scales = self._scatter_slot_pages(
            jax.tree.leaves(state["pool"]), jax.tree.leaves(new_views),
            self._cache_axes, table, active, state["pos"], K, K // P + 2,
            scales)
        dpool_leaves, dscales = self._scatter_slot_pages(
            jax.tree.leaves(state["dpool"]), jax.tree.leaves(new_dviews),
            self._draft_cache_axes, dtable, active, state["pos"], K - 1,
            (K - 1) // P + 2, dscales)
        state = dict(
            state,
            pool=jax.tree.unflatten(self._cache_struct, pool_leaves),
            dpool=jax.tree.unflatten(self._draft_cache_struct, dpool_leaves),
            pos=jnp.where(active, state["pos"] + ns, state["pos"]),
            tok=jnp.where(active, toks, state["tok"]),
            rng=jnp.where(active[:, None], rngs, state["rng"]),
            done=jnp.where(active, dones, state["done"]),
        )
        if scales is not None:
            state["pscale"] = scales
        if dscales is not None:
            state["dpscale"] = dscales
        return state, emit, ns

    def _spec_lookup_fn(self, params, state, active, table, remaining,
                        proposals, bank=None):
        """One SPECULATIVE tick, prompt-lookup mode: ``proposals`` [S, K]
        arrive as traced host data (the n-gram matcher runs in numpy —
        see :meth:`_lookup_proposals`), so the program is just the target
        verify + accept: no draft model, no draft KV, no second pool. A
        miss proposes garbage the verifier rejects at its first token —
        correctness never depends on proposal quality. Returns
        ``(state, emitted [S, K+1], n [S])`` like :meth:`_spec_fn`."""
        P, K = self._page, self._spec_k
        params = self._dq(params)
        scales = state.get("pscale")
        views = self._gather_views_all_slots(state["pool"], table,
                                             scales=scales)

        def one_slot(view, tok, pos, done, rem, rng, drafts, aidx=None):
            drafts = drafts.astype(tok.dtype)
            ids_v = jnp.concatenate([tok[None], drafts])[None]
            logits, view = self.module.apply(
                {"params": params}, ids_v, cache=view, cache_pos=pos,
                **self._lora_kwargs(bank, aidx))
            emit, n, new_tok, new_done, rng = self._spec_accept(
                logits[0], drafts, done, rem, rng)
            return view, new_tok, n, emit, new_done, rng

        vmap_args = [views, state["tok"], state["pos"], state["done"],
                     remaining, state["rng"], proposals]
        if bank is not None:
            vmap_args.append(state["adapter_idx"])
        new_views, toks, ns, emit, dones, rngs = jax.vmap(one_slot)(
            *vmap_args)
        pool_leaves, scales = self._scatter_slot_pages(
            jax.tree.leaves(state["pool"]), jax.tree.leaves(new_views),
            self._cache_axes, table, active, state["pos"], K, K // P + 2,
            scales)
        state = dict(
            state,
            pool=jax.tree.unflatten(self._cache_struct, pool_leaves),
            pos=jnp.where(active, state["pos"] + ns, state["pos"]),
            tok=jnp.where(active, toks, state["tok"]),
            rng=jnp.where(active[:, None], rngs, state["rng"]),
            done=jnp.where(active, dones, state["done"]),
        )
        if scales is not None:
            state["pscale"] = scales
        return state, emit, ns

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self):
        """Spawn the engine thread (idempotent) and run warmup traffic."""
        if self._thread is not None:
            return
        if self._compile_watcher is None:
            # Black-box compile accounting: any XLA compile while this
            # replica serves is a flight event (a steady-state compile is
            # the zero-recompile invariant breaking in production).
            # Unregistered in shutdown() AND the run loop's finally, so a
            # killed engine never leaks its process-global listener.
            from ..utils.profiling import CompileWatcher

            self._compile_watcher = CompileWatcher(
                on_event=lambda event, duration_s: self._flight.record(
                    "compile", event=event, duration_s=duration_s))
            self._compile_watcher.start()
        self._accepting = True
        self._heartbeat = (self._loop_iters, time.monotonic())
        self._heartbeat_frozen = False
        if self._async and (self._emitter is None or not self._emitter.alive):
            self._emitter = _TokenEmitter(self._emission_queue)
        self._thread = threading.Thread(target=self._run,
                                        name="serving-engine", daemon=True)
        self._thread.start()
        if self._warmup_on_start:
            self.warmup()

    def warmup(self, timeout: float = 120.0):
        """Compile every steady-state program by pushing dummy requests
        through the normal path: one chunk call + one decode tick, and —
        when a multi-chunk prompt fits the engine at all — two identical
        two-chunk prompts so the second one's prefix hit compiles
        ``restore_prefix`` too. ``ignore_eos`` keeps the dummies decoding
        even if the model emits eos immediately. Counters reset and the
        prefix cache is cleared afterwards so warmup traffic never
        pollutes serving metrics (or lingers as phantom cached prefixes)."""
        req = self.submit(np.zeros((1, 1), np.int32), max_new_tokens=2,
                          seed=0, ignore_eos=True, block=True)
        if not req.wait(timeout):
            raise TimeoutError("engine warmup did not finish "
                               f"within {timeout}s")
        self._raise_if_failed(req)
        if (self._chunk is not None and self._prefix_cache is not None
                and self._chunk + 2 <= self._chunk_limit):
            ids = np.zeros((1, self._chunk + 1), np.int32)
            for _ in range(2):
                r = self.submit(ids, max_new_tokens=1, seed=0,
                                ignore_eos=True, block=True)
                if not r.wait(timeout):
                    raise TimeoutError("engine warmup did not finish "
                                       f"within {timeout}s")
                self._raise_if_failed(r)
        self._stats.reset()
        if self._prefix_cache is not None:
            self._prefix_cache.clear()
        # Warmup traffic (and its compiles) must not pollute traces,
        # postmortems, or the compile counters, same as the stats reset.
        self._tracer.clear()
        self._flight.clear()
        self._next_profile_tick = self._decode_ticks + 1
        if self._compile_watcher is not None:
            self._compile_watcher.reset()

    @staticmethod
    def _raise_if_failed(req):
        if req.status != RequestStatus.COMPLETED:
            raise RuntimeError(f"warmup request {req.status.value}") from req.error

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the engine. ``drain=True`` finishes every accepted request
        (queued and running) first; ``drain=False`` cancels them. Either
        way, blocks for the engine thread (up to ``timeout``) and then
        drains in-flight async checkpoint saves — a serving process is
        often the same process that just trained the weights it serves,
        and exiting with Orbax writes still in flight drops them."""
        from .. import checkpointing

        self._accepting = False
        if drain:
            self._drain = True
        else:
            self._stop = True
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # The run loop's finally also closes the queue; doing it here too
        # covers an engine that was never started (autostart=False), so a
        # blocked submit can never outlive the engine either way.
        self._queue.close()
        if self._emitter is not None:
            # Drain-then-join (idempotent — the run loop's finally already
            # closed it on a normal exit): buffered tokens and deferred
            # completions are delivered, never dropped.
            self._emitter.close(timeout)
        self._stop_compile_watcher()
        if self._trace_dir is not None and self._error is None:
            self._dump_debug_files()
        checkpointing.wait_for_saves()
        if self._error is not None:
            raise RuntimeError("serving engine died") from self._error

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    @property
    def healthy(self) -> bool:
        """Live and serviceable: the engine thread is running, no fatal
        error has been recorded, and admission is open. The router's
        health checks key off this."""
        return self.running and self._error is None and self._accepting

    @property
    def error(self) -> Optional[BaseException]:
        """The fatal error that killed the run loop, if any."""
        return self._error

    @property
    def free_slots(self) -> int:
        """Decode lanes currently unoccupied (router free-slot routing)."""
        return self._slots.free_slots

    @property
    def queue_depth(self) -> int:
        """Requests waiting for admission right now."""
        return len(self._queue)

    @property
    def paged(self) -> bool:
        """Whether this engine uses the paged KV pool."""
        return self._paged

    @property
    def page_size(self) -> Optional[int]:
        """Tokens per KV page (None for dense engines)."""
        return self._page

    @property
    def total_pages(self) -> int:
        """Usable pool pages (0 for dense engines)."""
        return self._pool.num_pages if self._paged else 0

    @property
    def free_pages(self) -> int:
        """Unallocated pool pages right now (0 for dense engines — their
        capacity is slots, which ``free_slots`` already reports)."""
        return self._pool.free_pages if self._paged else 0

    @property
    def _spec_page_factor(self) -> int:
        """Pages-per-token multiplier for admission math: a draft-model
        speculative engine allocates a DRAFT page alongside every target
        page (same pool, same id space), so its real per-request footprint
        is double the token count's page cost. Lookup-mode speculation
        drafts from host data and costs nothing extra."""
        return 2 if self._spec_mode == "draft" else 1

    def page_deficit(self, total_tokens: int) -> int:
        """How many pages this engine is SHORT for a request of
        ``total_tokens`` (prompt + max_new): 0 means the pool can hold it
        right now, >0 means admitting it would lean on preemption. Dense
        engines reserve a full max_len row per slot, so they are never
        page-starved (0). The router folds this into its least-loaded
        score so long prompts route to replicas with free pages — and a
        draft-speculating replica reports its doubled footprint
        (:attr:`_spec_page_factor`), so the router never over-admits it
        relative to its real pool pressure."""
        if not self._paged or total_tokens <= 0:
            return 0
        needed = (-(-int(total_tokens) // self._page)
                  * self._spec_page_factor)
        return max(0, needed - self._pool.free_pages)

    @property
    def heartbeat(self) -> tuple:
        """``(loop_iterations, wall_time)`` published by the run loop at
        the top of EVERY iteration (idle iterations included — the loop
        polls the queue at ``idle_poll_s``, so a live engine republishes
        many times a second) AND at every reconcile barrier — so under
        one-tick-ahead dispatch a wedge inside the dispatched call still
        stalls the heartbeat within one tick. A watchdog that sees the
        wall time stall while :attr:`error` stays None is looking at a
        HUNG engine — e.g. a compiled call that never returned — which
        lazy health checks can never catch (see
        :class:`~.supervisor.FleetSupervisor`)."""
        return self._heartbeat

    @property
    def decode_ticks(self) -> int:
        """Decode ticks executed since construction — the deterministic
        clock :class:`~.chaos.ChaosSchedule` keys scripted faults on
        (ticks advance with token progress, unlike wall time)."""
        return self._decode_ticks

    def page_drain_rate(self, window_s: float = 15.0) -> float:
        """Observed pool page-free rate (pages/second) over the last
        ``window_s`` of decode ticks, 0.0 when dense or not yet observed.
        The gateway divides a projected page deficit by this to derive
        Retry-After for a pressure shed — "the pool frees ~N pages/s, so
        your M-page deficit clears in about M/N seconds"."""
        if not self._paged:
            return 0.0
        samples = list(self._drain_samples)
        if len(samples) < 2:
            return 0.0
        now = time.monotonic()
        recent = [s for s in samples if now - s[0] <= window_s]
        if len(recent) < 2:
            recent = samples[-2:]
        (t0, f0), (t1, f1) = recent[0], recent[-1]
        if t1 <= t0 or f1 <= f0:
            return 0.0
        return (f1 - f0) / (t1 - t0)

    def projected_page_deficit(self, total_tokens: int) -> int:
        """Pages the pool is short if this request is admitted BEHIND the
        work already queued: ``ceil(total_tokens / page) + ceil(queued
        footprint / page) - free_pages``, floored at 0 (dense engines are
        never short). Unlike :meth:`page_deficit` this counts the
        admission queue's projected demand too — the signal behind the
        gateway's projected-pressure 429 (ROADMAP's "429 on projected
        pool pressure rather than queue depth")."""
        if not self._paged or total_tokens <= 0:
            return 0
        factor = self._spec_page_factor
        needed = -(-int(total_tokens) // self._page) * factor
        queued = -(-int(self._queue.pending_tokens) // self._page) * factor
        return max(0, needed + queued - self._pool.free_pages)

    @property
    def load(self) -> float:
        """Occupancy fraction over the engine's whole admission capacity:
        ``(active slots + queued) / (max_slots + max_queued)`` — the
        router's least-loaded score; 1.0 means a submit would bounce. A
        paged engine also folds in POOL pressure (used/total pages), so
        the router steers traffic away from a replica whose memory, not
        slots, is the bottleneck."""
        base = ((self._slots.active_slots + len(self._queue))
                / (self.max_slots + self._queue.max_queued))
        if self._paged:
            return max(base, self._pool.used_pages / self._pool.num_pages)
        return base

    def kill(self, error: Optional[BaseException] = None):
        """Fault injection / fencing: make the run loop raise ``error`` at
        its next iteration, exactly as a device failure inside a compiled
        call would — the engine records the error, fails every in-flight
        and queued request, and exits. Used by the failover tests/benches
        and by operators fencing a suspect replica hard (prefer
        :meth:`shutdown` for anything gentler)."""
        err = error if error is not None else RuntimeError(
            "replica killed by fault injection")
        self._flight.record("kill", error=repr(err))
        self._fail_injection = err

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt_ids=None, *, request: Optional[Request] = None,
               max_new_tokens: int = 20, seed: Optional[int] = None,
               rng=None, timeout: Optional[float] = None, on_token=None,
               ignore_eos: bool = False, adapter: Optional[str] = None,
               trace_id: Optional[str] = None,
               priority: Optional[str] = None, block: bool = False,
               block_timeout: Optional[float] = None) -> Request:
        """Enqueue one request; returns its :class:`Request` handle
        immediately. Raises :class:`scheduler.QueueFull` under backpressure
        when ``block=False``; with ``block=True`` the caller waits for
        queue space instead (up to ``block_timeout``). A pre-built
        ``request=`` handle must be FRESH: handles are single-use, and
        resubmitting one that is queued, in flight, or already retired
        raises ``ValueError`` (its tokens/status/events are stale state a
        second flight would corrupt)."""
        if request is None:
            request = Request(prompt_ids, max_new_tokens=max_new_tokens,
                              rng=rng, seed=seed, timeout=timeout,
                              on_token=on_token, ignore_eos=ignore_eos,
                              adapter=adapter, trace_id=trace_id,
                              priority=priority)
        elif (request.status is not RequestStatus.QUEUED
                or request.submitted_at is not None):
            raise ValueError(
                f"Request handle already used (status "
                f"{request.status.value}); Request objects are single-use — "
                "build a fresh Request (or pass prompt_ids) per submission")
        if request.adapter is not None:
            if self._adapters is None:
                raise ValueError(
                    f"request names adapter {request.adapter!r} but this "
                    "engine has no adapter bank (pass adapters=AdapterBank(...))")
            # Unknown names raise UnknownAdapterError (a LookupError) here,
            # synchronously — the gateway maps it to HTTP 404.
            self._adapters.check_known(request.adapter)
        if (not self._accepting or self._stop or self._drain
                or self._queue.closed):
            raise RuntimeError("serving engine is not accepting requests "
                               "(not started, shutting down, or preempted)")
        S = request.prompt_ids.shape[1]
        if S < 1:
            raise ValueError("empty prompt")
        if S + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({request.max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len}); resize the "
                "engine or shorten the request")
        if self._paged:
            # A lone request must always be satisfiable: with everyone else
            # preempted and the alias cache drained, its worst-case footprint
            # has to fit the pool, or admission could wedge forever.
            need = (-(-(S + request.max_new_tokens) // self._page)
                    * self._spec_page_factor)
            if need > self._pool.num_pages:
                raise ValueError(
                    f"request needs up to {need} KV pages (prompt {S} + "
                    f"max_new_tokens {request.max_new_tokens} at page_size "
                    f"{self._page}"
                    + (", doubled for draft KV pages"
                       if self._spec_page_factor > 1 else "")
                    + f") but the pool only has "
                    f"{self._pool.num_pages}; raise max_pages or shorten "
                    "the request")
        if self._spec_k is not None:
            # A verify near the end of the stream writes positions up to
            # (S + max_new - 1) + K; the draft scan stops one short.
            K = self._spec_k
            _check_position_bound(self.module, S + request.max_new_tokens + K)
            if self._draft_module is not None:
                _check_position_bound(self._draft_module,
                                      S + request.max_new_tokens + K - 1)
        else:
            _check_position_bound(self.module, S + request.max_new_tokens)
        if request.trace_id is None:
            # Engine-direct submissions get an id too, so dump_trace can
            # always filter per request (the gateway mints upstream).
            request.trace_id = new_trace_id()
        request.submitted_at = time.monotonic()
        try:
            self._queue.put(request, block=block, timeout=block_timeout)
        except QueueFull:
            self._stats.record_reject()
            raise
        except QueueClosed as e:
            # The engine stopped between the accepting-check above and the
            # enqueue (or while we were blocked waiting for space): same
            # contract as submitting to a dead engine outright.
            raise RuntimeError(
                "serving engine is not accepting requests "
                "(not started, shutting down, or preempted)") from e
        self._stats.record_submit(len(self._queue))
        if request.priority is not None:
            self._stats.record_priority_request(request.priority)
        args = {"prompt_len": S, "queue_depth": len(self._queue)}
        if request.priority is not None:
            args["priority"] = request.priority
        self._tracer.instant("submit", trace_id=request.trace_id, args=args)
        return request

    def serving_metrics(self) -> dict:
        """Scalar snapshot of the engine's counters (see
        :class:`metrics.ServingStats.summary`)."""
        return self._stats.summary()

    @property
    def stats(self) -> ServingStats:
        return self._stats

    @property
    def prefix_cache(self) -> Optional[PrefixCache]:
        return self._prefix_cache

    # -- observability ---------------------------------------------------
    @property
    def tracer(self) -> Tracer:
        """This engine's span tracer (request-scoped timeline sink)."""
        return self._tracer

    @property
    def flight_recorder(self) -> FlightRecorder:
        """This engine's black box (last-N structured lifecycle events)."""
        return self._flight

    @property
    def compile_watcher(self):
        """The engine's :class:`~accelerate_tpu.utils.profiling.
        CompileWatcher` (None before :meth:`start`). Its counters answer
        "did serving compile anything after warmup" — 0 at steady state
        is the zero-recompile invariant, now observable in production."""
        return self._compile_watcher

    def trace_events(self, trace_id: Optional[str] = None) -> list:
        """Snapshot of buffered span records (see :meth:`Tracer.events`)."""
        return self._tracer.events(trace_id)

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        """Chrome-trace/Perfetto JSON dict of the buffered spans."""
        return self._tracer.chrome_trace(trace_id)

    def dump_trace(self, path: str, trace_id: Optional[str] = None) -> str:
        """Write the Chrome-trace JSON to ``path``; returns ``path``.
        Load it at ``chrome://tracing`` or https://ui.perfetto.dev."""
        return self._tracer.dump(path, trace_id)

    def postmortem(self) -> Optional[dict]:
        """The flight-recorder dump auto-captured when the run loop died
        (None while the engine is healthy). The router attaches this to
        its failover report for the dead replica."""
        return self._postmortem

    def _stop_compile_watcher(self):
        watcher = self._compile_watcher
        if watcher is not None:
            watcher.stop()  # idempotent; shutdown() + run-loop finally race

    def _dump_debug_files(self):
        """Best-effort trace/flight dump into ``trace_dir`` (death or
        shutdown must never be masked by a full disk)."""
        try:
            os.makedirs(self._trace_dir, exist_ok=True)
            base = os.path.join(self._trace_dir, self._tracer.name)
            self._tracer.dump(base + "-trace.json")
            self._flight.dump_json(base + "-flight.json")
        except OSError:
            pass

    @property
    def adapters(self) -> Optional[AdapterBank]:
        return self._adapters

    def register_adapter(self, name: str, adapter, **kwargs) -> None:
        """Register a named LoRA adapter with this engine's bank (host-side;
        the device load happens lazily at first use)."""
        if self._adapters is None:
            raise RuntimeError(
                "engine has no adapter bank; construct it with "
                "adapters=AdapterBank(params, ...)")
        self._adapters.register(name, adapter, **kwargs)

    def adapter_resident(self, name: str) -> bool:
        """Whether ``name`` currently occupies a bank row (router affinity)."""
        return self._adapters is not None and self._adapters.resident(name)

    @property
    def kv_dtype(self) -> Optional[str]:
        """``"int8"`` when KV pages are stored quantized; None = the
        bit-exact full-precision pool."""
        return self._kv_dtype

    @property
    def weights_dtype(self) -> Optional[str]:
        """``"int8"`` when base weights are stored quantized (LoRA path
        full precision); None = full-precision weights."""
        return self._weights_dtype

    def kv_cache_per_chip_bytes(self) -> int:
        """Per-device byte footprint of the decode KV state (max shard per
        leaf): the HBM-planning number, ≈ ``1/tp`` of the single-chip
        figure for heads-sharded leaves (docs/performance.md). For a
        paged engine this is the page POOL — the number ``max_pages``
        controls directly, independent of ``max_slots`` — plus the
        per-page scale arrays on a quantized engine (they're replicated,
        so they count at full size per chip)."""
        tree = (self._state["pool"] if self._paged
                else self._state["cache"])
        extra = sum(self._state[k].nbytes for k in ("pscale", "dpscale")
                    if k in self._state)
        if self._exec is not None:
            return self._exec.per_chip_bytes(tree) + extra
        return sum(l.nbytes for l in jax.tree.leaves(tree)) + extra

    def page_pool_metrics(self) -> dict:
        """Host-side pool snapshot (empty for dense engines): page size,
        totals, occupancy, allocation and preemption counters. On a
        quantized engine ``page_bytes`` is already the int8 figure
        (1 byte/element + 4-byte scale per leaf)."""
        if not self._paged:
            return {}
        out = {
            "page_size": self._page,
            "kv_dtype": self._kv_dtype,
            "pages_per_slot": self._pages_per_slot,
            "page_bytes": self._page_bytes,
            "pages_total": self._pool.num_pages,
            "pages_free": self._pool.free_pages,
            "pages_used": self._pool.used_pages,
            "page_allocations": self._pool.allocations,
            "preemptions": self._pool.preemptions,
        }
        if self._spec_mode == "draft":
            # Draft pages share the pool's id space but are smaller bytes:
            # capacity planning needs both figures.
            out["draft_page_bytes"] = self._draft_page_bytes
        return out

    def decode_memory_analysis(self):
        """``CompiledMemoryStats`` for the decode tick, compiled FRESH from
        the same function + shardings — lowering through the serving jit
        itself would add a cache entry and break the warm-executable
        accounting the zero-recompile tests pin."""
        args = [self.params, self._state,
                np.zeros((self.max_slots,), bool)]
        if self._paged:
            args.append(self._table.copy())
        if self._adapters is not None:
            args.append(self._adapters.stacks)
        decode_fn = self._paged_decode_fn if self._paged else self._decode_fn
        if self._exec is None:
            fn = jax.jit(decode_fn)
        else:
            rep = self._exec.replicated
            ins = [self._param_sh, self._state_sh, rep]
            if self._paged:
                ins.append(rep)
            if self._adapters is not None:
                ins.append(self._bank_sh)
            fn = self._exec.jit(decode_fn, tuple(ins),
                                (self._state_sh, rep, rep))
        return fn.lower(*args).compile().memory_analysis()

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------
    def _run(self):
        # The one in-flight dispatched tick (async mode; always None in
        # sync mode). Loop shape per iteration: sweeps → admission →
        # DISPATCH tick N+1 → RECONCILE tick N — so every piece of host
        # work between the two barriers overlaps tick N+1's device time.
        flight: Optional[_TickFlight] = None
        try:
            while not self._stop:
                # Liveness first: apply any scripted chaos (which may set
                # the fail injection we check next), then publish the
                # heartbeat — unless a chaos hang suppresses it, in which
                # case a watchdog sees exactly what a wedged compiled call
                # looks like while the loop itself keeps serving.
                self._loop_iters += 1
                if self._chaos is not None:
                    self._chaos.apply(self)
                if not self._heartbeat_frozen:
                    self._heartbeat = (self._loop_iters, time.monotonic())
                if self._fail_injection is not None:
                    # Routed through the normal engine-fatal path below, so
                    # an injected fault is indistinguishable from a real one
                    # to everything downstream (router fencing included).
                    raise self._fail_injection
                if (self._accelerator is not None
                        and getattr(self._accelerator, "preemption_requested", False)
                        and not (self._drain or self._abort_queue)):
                    # Preemption drain: stop admitting, let in-flight
                    # requests finish, cancel the queue — the notice window
                    # is for flushing work, not for taking more.
                    self._accepting = False
                    self._abort_queue = True
                now = time.monotonic()
                for _, req in self._slots.active():
                    if req._emit_error is not None:
                        # A streaming callback raised on the emitter
                        # thread: same FAILED retirement (slot freed,
                        # batch untouched) an inline failure produces.
                        self._retire(req, RequestStatus.FAILED,
                                     req._emit_error)
                    elif req.cancel_requested:
                        self._retire(req, RequestStatus.CANCELLED)
                    elif req._deadline_passed(now):
                        self._retire(req, RequestStatus.TIMED_OUT)
                if self._abort_queue:
                    for req in self._queue.drain():
                        self._finish_req(req, RequestStatus.CANCELLED)
                        self._stats.record_finish(req.status)
                # Bounded admission: spend at most chunks_per_tick chunk
                # calls, ALTERNATING one continuation of the PREFILLING
                # backlog (round-robin) with one new admission — so with a
                # budget of 2+, a fresh arrival's first chunk rides
                # alongside an in-flight long prefill instead of queueing
                # behind all of it. Monolithic mode (prefill_chunk=None)
                # has no budget — admission runs the whole prompt inline,
                # the behavior this PR A/Bs against.
                if self._chunk is None:
                    while self._slots.has_free():
                        req = self._queue.get_nowait()
                        if req is None:
                            break
                        if self._screen(req, now):
                            self._admit(req)
                else:
                    budget = self._chunks_per_tick
                    while budget > 0:
                        progressed = False
                        if self._advance_one_prefill():
                            budget -= 1
                            progressed = True
                        if budget > 0 and self._slots.has_free():
                            req = self._queue.get_nowait()
                            if req is not None:
                                progressed = True
                                if self._screen(req, now):
                                    budget = self._begin_prefill(req, budget)
                                    if budget is None:
                                        # Paged admission gate: the request
                                        # went back to the queue front; stop
                                        # admitting until decode frees pages.
                                        break
                        if not progressed:
                            break
                running = [(slot, req) for slot, req in self._slots.active()
                           if req.status is RequestStatus.RUNNING]
                if running:
                    if self._async:
                        if self._spec_mode == "lookup" and flight is not None:
                            # Prompt-lookup proposals must anchor on the
                            # NEWEST committed token: a proposal drafted
                            # ahead is misaligned by the in-flight tick's
                            # variable-length commit (1..K+1 tokens) and
                            # verifies to zero accepts, collapsing lookup
                            # speculation to dense decode. So lookup
                            # engines settle tick N before drafting N+1 —
                            # off-thread emission and the commit barrier
                            # are unchanged; only dispatch/device overlap
                            # is given up.
                            self._reconcile(flight)
                            flight = None
                            continue
                        # One tick ahead: dispatch N+1 against the
                        # in-flight state futures (host view stale by
                        # exactly the one unreconciled tick when
                        # ``flight`` exists), THEN settle tick N.
                        nxt = self._dispatch(running,
                                             ahead=flight is not None)
                        if flight is not None:
                            self._reconcile(flight)
                        flight = nxt
                        if flight is None:
                            # Nothing dispatched (every stream flow-
                            # controlled or preempted) and nothing in
                            # flight: yield so consumers can drain
                            # instead of hot-spinning the loop.
                            time.sleep(min(self._idle_poll_s, 0.001))
                    else:
                        # Sync A/B fallback: dispatch and immediately
                        # reconcile — the strictly tick-synchronous
                        # pre-async behavior, same commit path.
                        f = self._dispatch(running, ahead=False)
                        if f is not None:
                            self._reconcile(f)
                    continue
                if flight is not None:
                    # The last running streams retired/preempted out from
                    # under the in-flight tick — settle it (stray lanes
                    # discard; pages/stats still reconcile).
                    self._reconcile(flight)
                    flight = None
                    continue
                self._last_complete_t = None   # ITL intervals restart
                if self._slots.active_slots:
                    pass  # prefill-only batch: loop again without idling
                elif self._drain and not len(self._queue):
                    break
                elif self._abort_queue:
                    break
                else:
                    # Idle: block briefly on the queue so a submit wakes the
                    # loop without a hot spin. The popped request goes
                    # through the SAME screen as the busy path — one
                    # cancelled or deadline-expired while the engine idled
                    # must not be prefilled (or billed in stats).
                    req = self._queue.get(timeout=self._idle_poll_s)
                    if req is not None and self._screen(req, time.monotonic()):
                        if self._chunk is None:
                            self._admit(req)
                        else:
                            self._begin_prefill(req, self._chunks_per_tick)
        except BaseException as e:  # engine-fatal: fail everything loudly
            self._error = e
            # Black-box capture at the moment of death: the fatal event
            # plus the last N lifecycle events, frozen BEFORE the retire
            # sweep below — this dump is what the router attaches to its
            # failover report.
            self._flight.record("fatal", error=repr(e))
            self._postmortem = self._flight.dump()
            if self._trace_dir is not None:
                self._dump_debug_files()
        finally:
            self._stop_compile_watcher()
            self._accepting = False
            # Close BEFORE the final drain: wakes producers blocked in
            # put(block=True) with QueueClosed and guarantees nothing can
            # slip into the queue after we empty it below.
            self._queue.close()
            self._prefilling.clear()
            terminal = (RequestStatus.FAILED if self._error is not None
                        else RequestStatus.CANCELLED)
            for _, req in list(self._slots.active()):
                self._retire(req, terminal, self._error)
            for req in self._queue.drain():
                self._finish_req(req, terminal, self._error)
                self._stats.record_finish(req.status)
            if self._emitter is not None:
                # AFTER the retire sweep queued its deferred completions:
                # drain every buffered token and completion, then join —
                # failover handlers (``_on_finish``) all fire before the
                # engine thread exits.
                self._emitter.close()

    def _screen(self, req: Request, now: float) -> bool:
        """The check-then-admit gate both pop paths share: a request whose
        cancellation or deadline fired while it queued is finished here,
        never admitted."""
        if req.cancel_requested:
            self._finish_req(req, RequestStatus.CANCELLED)
        elif req._deadline_passed(now):
            self._finish_req(req, RequestStatus.TIMED_OUT)
        else:
            return True
        self._stats.record_finish(req.status)
        return False

    def _acquire_adapter(self, req: Request) -> bool:
        """Pin the request's adapter into a bank row before it takes a slot.

        Base requests (or bank-less engines) use row 0, the identity.
        Failure is REQUEST-fatal, never engine-fatal: an unknown name or a
        fully-pinned bank fails this request with the original exception
        (``engine.error`` stays None, so the router does not fail over) and
        the loop moves on."""
        if self._adapters is None or req.adapter is None:
            req._adapter_row = 0
            return True
        try:
            row, hit, evicted = self._adapters.acquire(req.adapter)
        except Exception as e:
            self._finish_req(req, RequestStatus.FAILED, e)
            self._stats.record_finish(req.status)
            return False
        req._adapter_row = row
        req._adapter_pinned = True
        self._stats.record_adapter_admit(req.adapter, hit=hit, evicted=evicted)
        if not hit:
            self._flight.record("adapter_load", adapter=req.adapter,
                                row=row, evicted=evicted,
                                trace_id=req.trace_id)
        return True

    def _adapter_args(self, req: Request) -> tuple:
        """Trailing (adapter_idx, bank) args for the prefill programs —
        empty for bank-less engines, so their call signature (and traced
        program) is exactly the pre-adapter one."""
        if self._adapters is None:
            return ()
        return (np.int32(req._adapter_row), self._adapters.stacks)

    # -- host-side page accounting (engine thread only) -----------------
    def _on_prefix_evict(self, key, value):
        """Alias-cache eviction hook: the evicted entry's value is the
        tuple of pool page ids the cache held a reference on — give them
        back. Pages still referenced by a live slot survive (refcounts);
        only the last reference frees."""
        for pid in value:
            self._pool.decref(int(pid))

    def _release_slot_pages(self, slot: int):
        """Drop the slot's reference on every table entry (draft-table
        entries too, when a draft model speculates) and clear the rows.
        Aliased pages shared with the prefix cache or other slots stay
        allocated until their last reference goes."""
        rows = [self._table[slot]]
        if self._dtable is not None:
            rows.append(self._dtable[slot])
        for row in rows:
            for idx in range(self._pages_per_slot):
                if row[idx]:
                    self._pool.decref(int(row[idx]))
            row[:] = 0

    def _alloc_page_into(self, req: Request, idx: int, table=None) -> bool:
        """Allocate one pool page into ``table[req.slot, idx]`` (the
        TARGET table by default; draft-mode callers pass ``self._dtable``
        — one pool, one id space). On exhaustion, first reclaim
        alias-cache entries LRU-first (an entry whose pages nobody else
        references frees real pages), then preempt other streams. False
        only when the requester is alone and the pool is still dry — which
        the submit-time page bound makes impossible, so callers treat it
        as an engine invariant violation."""
        table = self._table if table is None else table
        while True:
            pid = self._pool.alloc()
            if pid is not None:
                table[req.slot, idx] = pid
                return True
            if (self._alias_cache and self._prefix_cache is not None
                    and self._prefix_cache.evict_lru()):
                continue
            if not self._preempt_one(req):
                return False

    def _ensure_pages(self, req: Request, upto_pos: int) -> bool:
        """Make the slot's table cover position ``upto_pos`` (allocating
        every missing page up to and including its page)."""
        row = self._table[req.slot]
        # Indices below the request's window floor were freed on purpose
        # (sliding-window page lifetime) — never bring them back.
        for idx in range(req._page_floor, upto_pos // self._page + 1):
            if not row[idx]:
                if not self._alloc_page_into(req, idx):
                    return False
        return True

    def _ensure_draft_pages(self, req: Request, upto_pos: int) -> bool:
        """Draft-table twin of :meth:`_ensure_pages`. The draft cache is
        linear (no window floor): draft pages live for the stream's whole
        slot residency and are released with the target's."""
        row = self._dtable[req.slot]
        for idx in range(upto_pos // self._page + 1):
            if not row[idx]:
                if not self._alloc_page_into(req, idx, table=self._dtable):
                    return False
        return True

    def _reclaimable_pages(self) -> int:
        """Pages the admission gate could free without preempting anyone:
        alias-cache pages whose only reference is the cache's own."""
        if not (self._alias_cache and self._prefix_cache is not None):
            return 0
        return sum(
            1 for _, val in self._prefix_cache.entries()
            for pid in val if self._pool.refcount(int(pid)) == 1)

    def _preempt_one(self, requester: Request) -> bool:
        """Pool exhausted: evict another stream back to the FRONT of its
        queue class and free its pages. Victim selection is policy-driven:
        with a priority policy, the LOWEST-priority stream loses first and
        the newest-admitted within that class breaks the tie (least sunk
        prefill work, shortest resume); without a policy this degenerates
        to the historical newest-admitted rule. The victim resumes
        token-exactly later: its prompt becomes ``prompt + tokens`` (for
        greedy decoding the resumed prefill's first token IS the
        interrupted stream's next token — the router failover argument;
        sampled streams re-draw from the resume point). Returns False
        when no other stream holds a slot."""
        policy = self._priority_policy

        def _victim_key(r):
            rank = (policy.rank(getattr(r, "priority", None))
                    if policy is not None else 0)
            return (rank, r.admitted_at or 0.0)

        victim = None
        for _, r in self._slots.active():
            if r is requester:
                continue
            if victim is None or _victim_key(r) > _victim_key(victim):
                victim = r
        if victim is None:
            return False
        if victim.tokens:
            victim._serve_ids = np.concatenate(
                [victim.prompt_ids, np.asarray([victim.tokens], np.int32)],
                axis=1)
        self._release_slot_pages(victim.slot)
        self._slots.release(victim.slot)
        victim.slot = None
        if victim._adapter_pinned:
            victim._adapter_pinned = False
            self._adapters.release(victim.adapter)
        try:
            self._prefilling.remove(victim)
        except ValueError:
            pass
        victim.status = RequestStatus.QUEUED
        victim._preempted += 1
        self._pool.preemptions += 1
        self._stats.record_preemption()
        self._flight.record("preemption", trace_id=victim.trace_id,
                            tokens=len(victim.tokens),
                            free_pages=self._pool.free_pages)
        try:
            self._queue.putleft(victim)
        except QueueClosed:
            victim._finish(RequestStatus.CANCELLED)
            self._stats.record_finish(victim.status)
        return True

    def _free_window_pages(self, req: Request):
        """Sliding-window page lifetime: page ``j``'s last position is
        ``(j+1)*P - 1``; every future query sits at ``q >= pos``, and the
        model's window mask only attends ``k > q - window`` — so once
        ``(j+1)*P - 1 <= pos - window`` the page can never be read again
        and its reference is dropped (the zeroed table entry gathers
        scratch garbage, which that same mask excludes)."""
        pos = req._pos_base + len(req.tokens)
        row = self._table[req.slot]
        for j in range(self._pages_per_slot):
            if (j + 1) * self._page - 1 > pos - self._page_window:
                break
            if row[j]:
                self._pool.decref(int(row[j]))
                row[j] = 0
            req._page_floor = j + 1

    def _admit(self, req: Request):
        """Monolithic admission (``prefill_chunk=None``): host edge-pad to
        the 128 bucket (numpy — a jnp pad would compile per prompt
        length), run the whole prompt inline, and commit the first token.
        TTFT is stamped here because prefill itself emits token #1."""
        if not self._acquire_adapter(req):
            return
        req.admitted_at = time.monotonic()
        slot = self._slots.assign(req)
        self._flight.record("admission", trace_id=req.trace_id, slot=slot,
                            prompt_len=req.prompt_ids.shape[1],
                            adapter=req.adapter)
        req._serve_ids = req.prompt_ids
        S = req.prompt_ids.shape[1]
        P = self._bucket(S)
        ids_p = req.prompt_ids
        if P > S:
            ids_p = np.pad(ids_p, ((0, 0), (0, P - S)), mode="edge")
        rng = req.rng if req.rng is not None else jax.random.PRNGKey(
            req.seed if req.seed is not None else 0)
        self._state, tok = self._prefill(
            self.params, self._state, ids_p, np.int32(slot), rng, np.int32(S),
            *self._adapter_args(req))
        self._finish_prefill(req, int(tok))

    def _bucket(self, S: int) -> int:
        return max(min(_bucket128(S), self._chunk_limit), S)

    # -- chunked prefill ------------------------------------------------
    def _begin_prefill(self, req: Request, budget: int) -> Optional[int]:
        """Assign a slot, restore the longest cached chunk-aligned prefix
        (restores are not billed against the chunk budget — they are why
        the cache pays), and run the request's first live chunk. Returns
        the remaining budget — or ``None`` when the paged admission gate
        refuses: the prompt needs more pages than are free or reclaimable,
        so the request goes back to the queue FRONT and the caller stops
        admitting until decode progress frees pages (admitting anyway
        would just trigger preemption thrash).

        A paged engine prefills ``req._serve_ids`` — the original prompt,
        or prompt + committed tokens after a preemption — so the same code
        path is both first admission and token-exact resume."""
        if req._serve_ids is None:
            req._serve_ids = req.prompt_ids
        req._page_floor = 0  # every (re)admission prefills from page 0
        S = req._serve_ids.shape[1]
        C = self._chunk
        if self._paged:
            need = -(-S // self._page) * self._spec_page_factor
            if need > self._pool.free_pages + self._reclaimable_pages():
                self._flight.record(
                    "pool_exhausted", trace_id=req.trace_id,
                    need_pages=need, free_pages=self._pool.free_pages)
                try:
                    self._queue.putleft(req)
                except QueueClosed:
                    req._finish(RequestStatus.CANCELLED)
                    self._stats.record_finish(req.status)
                return None
        if not self._acquire_adapter(req):
            return budget
        req.admitted_at = time.monotonic()
        slot = self._slots.assign(req)
        self._flight.record("admission", trace_id=req.trace_id, slot=slot,
                            prompt_len=S, adapter=req.adapter,
                            resumed=bool(req.tokens))
        req.status = RequestStatus.PREFILLING
        req._rng_key = req.rng if req.rng is not None else jax.random.PRNGKey(
            req.seed if req.seed is not None else 0)
        req._chunks_total = -(-S // C)
        req._next_chunk = 0
        req._chunk_keys = None
        if self._prefix_cache is not None:
            n_full = S // C
            if n_full:
                req._chunk_keys = self._prefix_keys(req._serve_ids, n_full,
                                                    req.adapter)
            # The FINAL chunk always re-runs (cached blocks hold KV, not the
            # logits the first token needs), so at most chunks 0..n-2 restore.
            restorable = min(n_full, req._chunks_total - 1)
            if restorable:
                blocks = self._prefix_cache.match(req._chunk_keys[:restorable])
                restored_bytes = aliased = 0
                Cp = C // self._page if self._paged else 0
                for i, blk in enumerate(blocks):
                    if self._alias_cache:
                        # blk is a tuple of page ids: restoring is a host
                        # table write + refcount — zero device work. The
                        # pos-pin invariant holds because the first chunk
                        # call below runs before any tick can see the slot.
                        for j, pid in enumerate(blk):
                            self._pool.incref(int(pid))
                            self._table[slot, i * Cp + j] = int(pid)
                        restored_bytes += len(blk) * self._page_bytes
                        aliased += 1
                        continue
                    if self._paged:
                        ok = all(self._alloc_page_into(req, i * Cp + j)
                                 for j in range(Cp))
                        if not ok:
                            raise RuntimeError(
                                "page pool exhausted during prefix restore "
                                "with no preemptable stream — the submit "
                                "page bound should make this impossible")
                        pages_c = self._table[slot, i * Cp:(i + 1) * Cp]
                        self._state = self._restore_prefix(
                            self._state, blk, pages_c.astype(np.int32),
                            np.int32(slot), np.int32(S))
                    else:
                        self._state = self._restore_prefix(
                            self._state, blk, np.int32(slot), np.int32(i * C),
                            np.int32(S))
                    restored_bytes += sum(
                        l.nbytes for l in jax.tree.leaves(blk))
                if blocks and self._spec_mode == "draft":
                    # The cache holds TARGET KV only: draft KV is cheap to
                    # recompute and caching it would double every entry.
                    # Rebuild it for the restored span with the draft-only
                    # chunk program so a prefix-hit slot enters speculation
                    # with a warm draft cache.
                    for i in range(len(blocks)):
                        if not self._ensure_draft_pages(req, (i + 1) * C - 1):
                            raise RuntimeError(
                                "page pool exhausted during draft prefix "
                                "rebuild — the admission gate's draft "
                                "factor should make this impossible")
                        ids_c = req._serve_ids[:, i * C:(i + 1) * C]
                        self._state = self._draft_chunk(
                            self._draft_params, self._state, ids_c,
                            np.int32(slot),
                            self._dtable[req.slot].copy(),
                            np.int32(i * C))
                self._stats.record_prefix(looked_up=restorable,
                                          hit=len(blocks),
                                          bytes_restored=restored_bytes,
                                          aliased=aliased)
                if blocks:
                    self._tracer.instant(
                        "prefix_hit", trace_id=req.trace_id,
                        args={"chunks": len(blocks), "aliased": aliased,
                              "bytes": restored_bytes})
                req._next_chunk = len(blocks)
        self._prefilling.append(req)
        self._run_chunk(req)
        return budget - 1

    def _prefix_keys(self, prompt_ids, n_full: int,
                     adapter: Optional[str] = None) -> list[bytes]:
        """Hash-chain digests of the prompt's full chunks: chunk i's key
        covers tokens ``[0, (i+1)*C)`` because each digest folds in the
        previous one — equal keys mean equal whole prefixes, never just
        equal chunk contents. The chain is seeded with the request's
        adapter identity: a LoRA adapter changes the KV a prefix produces,
        so two tenants with byte-identical prompts must never share cached
        blocks (cross-tenant KV leak) — and, the same way, with the KV
        dtype: an int8 engine's pages carry quantization error a
        full-precision engine must never alias (and aliased int8 pages
        need the producing pool's scales, which an fp entry lacks)."""
        flat = np.ascontiguousarray(prompt_ids[0], np.int32)
        C = self._chunk
        seed = b"chunk:%d" % C
        if self._kv_dtype is not None:
            seed += b"/kv:" + self._kv_dtype.encode("utf-8")
        if adapter is not None:
            seed += b"/adapter:" + adapter.encode("utf-8")
        keys, prev = [], seed
        for i in range(n_full):
            prev = hashlib.blake2b(
                prev + flat[i * C:(i + 1) * C].tobytes(),
                digest_size=16).digest()
            keys.append(prev)
        return keys

    def cached_prefix_tokens(self, prompt_ids,
                             adapter: Optional[str] = None) -> int:
        """How many leading prompt tokens THIS engine could restore from
        its prefix cache right now — the router's cache-aware routing
        probe. Pure host work (hashing + dict lookups, no LRU promotion,
        no device calls), so probing every replica per dispatch is cheap
        and cannot perturb cache eviction order. Mirrors the restore
        bound in ``_begin_prefill``: the final chunk always re-runs, so
        at most ``ceil(S/C) - 1`` full chunks count."""
        if self._prefix_cache is None or self._chunk is None:
            return 0
        ids = np.asarray(prompt_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None, :]
        S = int(ids.shape[1])
        C = self._chunk
        restorable = min(S // C, -(-S // C) - 1)
        if restorable < 1:
            return 0
        keys = self._prefix_keys(ids, restorable, adapter)
        return self._prefix_cache.longest_prefix(keys) * C

    def _advance_one_prefill(self) -> bool:
        """Run ONE chunk for the oldest live entry of the PREFILLING
        backlog (round-robin: the entry requeues behind newer ones), so a
        short prompt's one-chunk prefill completes promptly even while a
        long prompt is mid-prefill — no head-of-line blocking inside
        admission either. Entries retired mid-prefill (cancel/timeout)
        are dropped lazily. Returns False when no live entry remains."""
        while self._prefilling:
            req = self._prefilling.popleft()
            if req.status is not RequestStatus.PREFILLING:
                continue
            self._run_chunk(req)
            if req.status is RequestStatus.PREFILLING:
                self._prefilling.append(req)
            return True
        return False

    def _run_chunk(self, req: Request):
        """One ``prefill_chunk`` call at the request's frontier. The final
        chunk's offset is pulled back (never past ``max_len - C`` / the
        position table) so the fixed width stays in bounds — re-running a
        few already-prefilled positions writes bit-identical KV. Full
        chunks feed the prefix cache with the block the executable already
        returned."""
        i = req._next_chunk
        C = self._chunk
        S = req._serve_ids.shape[1]
        final = i == req._chunks_total - 1
        offset = min(i * C, self._chunk_cap) if final else i * C
        ids_c = req._serve_ids[:, offset:offset + C]
        if ids_c.shape[1] < C:
            ids_c = np.pad(ids_c, ((0, 0), (0, C - ids_c.shape[1])),
                           mode="edge")
        t0 = time.monotonic()
        if self._paged:
            # Cover the chunk's whole write span (including the edge-pad
            # tail — decode writes land there next) before the call; the
            # program scatters only into these table entries.
            if not self._ensure_pages(req, offset + C - 1):
                raise RuntimeError(
                    "page pool exhausted mid-prefill with no preemptable "
                    "stream — the submit page bound should make this "
                    "impossible")
            extra = self._adapter_args(req)
            if self._spec_mode == "draft":
                if not self._ensure_draft_pages(req, offset + C - 1):
                    raise RuntimeError(
                        "page pool exhausted mid-prefill for draft KV — "
                        "the admission gate's draft factor should make "
                        "this impossible")
                extra += (self._draft_params, self._dtable[req.slot].copy())
            self._state, tok, block = self._prefill_chunk(
                self.params, self._state, ids_c, np.int32(req.slot),
                self._table[req.slot].copy(), np.int32(offset), np.int32(S),
                req._rng_key, *extra)
        else:
            self._state, tok, block = self._prefill_chunk(
                self.params, self._state, ids_c, np.int32(req.slot),
                np.int32(offset), np.int32(S), req._rng_key,
                *self._adapter_args(req))
        tb = time.monotonic()
        tok.block_until_ready()  # honest chunk timing, paced dispatch
        # The wait is device time (this chunk, plus any in-flight tick it
        # queued behind) — excluded from host_us_per_tick.
        self._blocked_s += time.monotonic() - tb
        dt_ms = (time.monotonic() - t0) * 1e3
        backlog = sum(1 for r in self._prefilling
                      if r.status is RequestStatus.PREFILLING)
        self._stats.record_prefill_chunk(dt_ms, backlog=backlog)
        self._tracer.emit(
            "prefill_chunk", t0, dt_ms / 1e3, trace_id=req.trace_id,
            args={"chunk": i, "of": req._chunks_total, "offset": offset,
                  "slot": req.slot, "backlog": backlog})
        if (self._prefix_cache is not None and req._chunk_keys is not None
                and offset == i * C and offset + C <= S):
            if self._alias_cache:
                # The cache entry is the chunk's PAGE IDS, not a KV copy:
                # a future hit aliases these very pages into another
                # slot's table. The cache takes its own reference on each
                # page (returned on eviction via the hook); a rejected or
                # duplicate put hands the references straight back.
                p0 = offset // self._page
                Cp = C // self._page
                pids = tuple(int(x)
                             for x in self._table[req.slot, p0:p0 + Cp])
                for pid in pids:
                    self._pool.incref(pid)
                if not self._prefix_cache.put(req._chunk_keys[i], pids,
                                              nbytes=Cp * self._page_bytes):
                    for pid in pids:
                        self._pool.decref(pid)
            else:
                if self._exec is not None:
                    # Host-portable blocks: a device_get'd chunk block
                    # restores into ANY slice's shardings via
                    # restore_prefix's in_shardings, so a fleet-shared
                    # PrefixCache serves cross-slice hits (the failover
                    # resume path).
                    block = jax.device_get(block)
                self._prefix_cache.put(
                    req._chunk_keys[i], block,
                    nbytes=sum(l.nbytes for l in jax.tree.leaves(block)))
            self._stats.record_prefix_cache_size(self._prefix_cache.nbytes,
                                                 len(self._prefix_cache))
        req._next_chunk = i + 1
        if final:
            self._finish_prefill(req, int(tok))

    def _finish_prefill(self, req: Request, token: int):
        """Prompt fully in KV: the request starts decoding. TTFT is stamped
        here because the final prefill call emits token #1 — but only on
        the FIRST completion: a preemption-resumed request already has
        tokens and an admit record, and must not be billed twice."""
        req.status = RequestStatus.RUNNING
        now = time.monotonic()
        if req.first_token_at is None:
            req.first_token_at = now
            self._stats.record_admit(
                queue_wait_ms=(req.admitted_at - req.submitted_at) * 1e3,
                ttft_ms=(now - req.submitted_at) * 1e3)
            self._tracer.emit(
                "queue_wait", req.submitted_at,
                req.admitted_at - req.submitted_at,
                trace_id=req.trace_id, args={"slot": req.slot})
            self._tracer.instant(
                "first_token", trace_id=req.trace_id,
                args={"ttft_ms": round((now - req.submitted_at) * 1e3, 3)})
        # Host mirror of the device write position: after this commit,
        # pos = serve length + 0 more; each committed token adds one.
        req._pos_base = req._serve_ids.shape[1] - len(req.tokens) - 1
        if self._commit_token(req, token):
            if (len(req.tokens) >= req.max_new_tokens
                    or (not req.ignore_eos and self.eos_token_id is not None
                        and token == self.eos_token_id)):
                self._retire(req, RequestStatus.COMPLETED)

    def _dispatch(self, running, ahead: bool) -> Optional[_TickFlight]:
        """Dispatch one decode tick and return its flight WITHOUT waiting
        for the device. ``running`` is the (slot, request) list in RUNNING
        — PREFILLING slots ride along in the vmapped forward (fixed
        shape) but are masked out of every state advance and commit no
        tokens. Paged engines first guarantee every dispatched slot's
        write position has a page (allocating — and preempting on
        exhaustion — at this dispatch boundary), then pass a page-table
        SNAPSHOT as traced data (the double buffer: reconcile-time frees
        mutate the live table, never the in-flight copy).

        ``ahead=True`` means one unreconciled tick is in flight, so host
        state (``len(req.tokens)``, page frontier) is stale by exactly
        one committed token per stream. The speculative view is made safe
        by two conservative rules: a stream within one token of its
        budget is EXCLUDED (it deterministically retires at the in-flight
        tick; dispatching it would write at a position past its bound),
        and page coverage extends one position past the stale frontier
        (the in-flight commit's write). A stream that instead retires on
        EOS at the in-flight tick stays masked in — its lane advances
        once more and the stray token is discarded by the reconcile
        validity check (exactly-once emission)."""
        if self._spec_k is not None:
            return self._dispatch_spec(running, ahead)
        live = []
        for slot, req in running:
            if ahead and req.max_new_tokens - len(req.tokens) <= 1:
                continue  # retires at the in-flight tick (position bound)
            if (self._emitter is not None and req.on_token is not None
                    and self._emitter.backlogged(req)):
                # Flow control: the consumer is emission_queue callbacks
                # behind — hold this stream back (its device state stays
                # put; the stream resumes bit-exactly) rather than buffer
                # without bound or stall the batch.
                self._stats.record_emission_stall()
                continue
            live.append((slot, req))
        if self._paged:
            for slot, req in live:
                if req.status is not RequestStatus.RUNNING:
                    continue  # preempted by an earlier slot's allocation
                upto = (req._pos_base + len(req.tokens)
                        + (1 if ahead else 0))
                if not self._ensure_pages(req, upto):
                    raise RuntimeError(
                        "page pool exhausted at a tick with no preemptable "
                        "stream — the submit page bound should make this "
                        "impossible")
            live = [(s, r) for s, r in live
                    if r.status is RequestStatus.RUNNING]
        if not live:
            return None
        mask = np.zeros((self.max_slots,), bool)
        for slot, _ in live:
            mask[slot] = True
        t0 = time.monotonic()
        args = [self.params, self._state, jnp.asarray(mask)]
        if self._paged:
            args.append(self._table.copy())
        if self._adapters is not None:
            args.append(self._adapters.stacks)
        self._state, toks, dones = self._decode(*args)
        return _TickFlight(
            entries=[(slot, req, req._preempted) for slot, req in live],
            t_dispatch=t0, toks=toks, dones=dones)

    def _reconcile(self, flight: _TickFlight):
        """Settle a dispatched tick: block until its tokens materialize
        (the one device sync point), then commit/retire on the host. An
        entry whose request is no longer RUNNING, or whose preemption
        epoch moved, is a stray lane — its token is discarded, which is
        what makes one-tick-ahead dispatch exactly-once.

        Timing: ``itl`` is the device-complete→device-complete interval
        (what a consumer experiences between tokens), and
        ``host_us_per_tick`` is that interval minus every blocked device
        wait since the previous reconcile — the host scheduling + commit
        wall the async runtime hides under device time."""
        if self._wedge_s:
            # Chaos: wedge INSIDE the reconcile barrier of a dispatched
            # call — the loop stops publishing heartbeats mid-"device
            # wait", exactly what a hung collective looks like.
            w, self._wedge_s = self._wedge_s, 0.0
            time.sleep(w)
        spec = flight.emit is not None
        tb = time.monotonic()
        if spec:
            emit = np.asarray(flight.emit)
            ns = np.asarray(flight.ns)
        else:
            toks = np.asarray(flight.toks)
            dones = np.asarray(flight.dones)
        t1 = time.monotonic()
        self._blocked_s += t1 - tb
        if not self._heartbeat_frozen:
            # Reconcile-barrier heartbeat: between loop tops the engine
            # may sit in this block for a whole device tick — republish
            # so the watchdog clock tracks real liveness.
            self._heartbeat = (self._loop_iters, t1)
        prev = self._last_complete_t
        interval = t1 - (prev if prev is not None else flight.t_dispatch)
        self._last_complete_t = t1
        host_s = max(0.0, interval - self._blocked_s)
        self._blocked_s = 0.0
        committed = accepted = n_valid = 0
        for slot, req, epoch in flight.entries:
            if (req.status is not RequestStatus.RUNNING
                    or req._preempted != epoch):
                continue  # stray lane: retired/preempted since dispatch
            n_valid += 1
            if spec:
                n = int(ns[slot])
                accepted += n - 1
                retired = False
                for j in range(n):
                    token = int(emit[slot, j])
                    if not self._commit_token(req, token):
                        retired = True
                        break
                    committed += 1
                    if (len(req.tokens) >= req.max_new_tokens
                            or (not req.ignore_eos
                                and self.eos_token_id is not None
                                and token == self.eos_token_id)):
                        self._retire(req, RequestStatus.COMPLETED)
                        retired = True
                        break
                if not retired and self._page_window is not None:
                    self._free_window_pages(req)
            else:
                if not self._commit_token(req, int(toks[slot])):
                    continue  # callback failed; slot already freed
                committed += 1
                if (len(req.tokens) >= req.max_new_tokens
                        or (not req.ignore_eos and bool(dones[slot]))):
                    self._retire(req, RequestStatus.COMPLETED)
                elif self._page_window is not None:
                    self._free_window_pages(req)
        if spec:
            self._stats.record_spec(
                proposed=self._spec_k * n_valid, accepted=accepted,
                lookup_hits=(flight.lookup_hits
                             if self._spec_mode == "lookup" else None),
                lookup_slots=(n_valid if self._spec_mode == "lookup"
                              else 0))
        self._decode_ticks += 1
        self._stats.record_tick(active_slots=len(flight.entries),
                                committed_tokens=committed,
                                max_slots=self.max_slots, seconds=interval,
                                host_us=host_s * 1e6)
        tracer = self._tracer
        if tracer.enabled:
            targs = {"active": len(flight.entries), "committed": committed,
                     "host_us": round(host_s * 1e6, 1)}
            if spec:
                targs["spec_accepted"] = accepted
            tracer.emit("decode_tick", flight.t_dispatch,
                        t1 - flight.t_dispatch, args=targs)
            for slot, req, _ in flight.entries:
                iargs = {"slot": slot, "token": len(req.tokens)}
                if spec:
                    iargs["accepted"] = int(ns[slot]) - 1
                tracer.emit("itl", t1 - interval, interval,
                            trace_id=req.trace_id, args=iargs)
        if self._decode_ticks >= self._next_profile_tick:
            # Black-box sample of the split ITL (cheap: one flight event
            # per ~128 ticks) — postmortems show whether host overhead or
            # device time dominated when things went sideways.
            self._next_profile_tick = self._decode_ticks + 128
            self._flight.record("tick_profile", tick=self._decode_ticks,
                                itl_ms=round(interval * 1e3, 3),
                                host_us=round(host_s * 1e6, 1),
                                active=len(flight.entries))
        if self._paged:
            self._drain_samples.append((time.monotonic(), self._pool.frees))
            self._stats.record_pages(self._pool.free_pages,
                                     self._pool.used_pages,
                                     self._pool.num_pages,
                                     freed_total=self._pool.frees)

    def _dispatch_spec(self, running, ahead: bool) -> Optional[_TickFlight]:
        """Speculative twin of :meth:`_dispatch`: dispatch one draft-scan
        + verify tick (up to ``spec_tokens + 1`` tokens per slot) without
        waiting. Page coverage is guaranteed only up to the furthest
        position a slot can COMMIT — overshoot writes route to scratch
        inside the program. Reconcile commits the emitted chain exactly
        like ``n`` dense ticks would: stop at ``max_new_tokens`` or the
        first eos.

        The ``ahead`` staleness rules: a stream with fewer than 2 budget
        tokens is excluded (it deterministically retires at the in-flight
        tick); page coverage extends to two chains' worth of commits
        (``min(2*(K+1), remaining)``) because the in-flight tick may
        advance the write frontier by a full chain before this one runs;
        and ``remaining`` is passed STALE — safe because it is always >=
        the true budget, and the device clamp only matters when it binds
        BELOW a chain length, which stale-high values never spuriously do
        (the host commit loop enforces the true budget; a retiring tick's
        device over-advance is stray state that dies with the slot).
        Lookup mode never dispatches ahead (the run loop reconciles
        first): a proposal drafted one tick behind is misaligned by the
        in-flight tick's variable-length commit and verifies to zero
        accepts, so ahead lookup would be exact but never faster than
        dense decode."""
        K = self._spec_k
        live = []
        for slot, req in running:
            if ahead and req.max_new_tokens - len(req.tokens) < 2:
                continue  # retires at the in-flight tick (position bound)
            if (self._emitter is not None and req.on_token is not None
                    and self._emitter.backlogged(req)):
                self._stats.record_emission_stall()
                continue
            live.append((slot, req))
        for slot, req in live:
            if req.status is not RequestStatus.RUNNING:
                continue
            rem = max(req.max_new_tokens - len(req.tokens), 1)
            span = min((2 if ahead else 1) * (K + 1), rem)
            cover = req._pos_base + len(req.tokens) + span - 1
            if not self._ensure_pages(req, cover):
                raise RuntimeError(
                    "page pool exhausted at a speculative tick with no "
                    "preemptable stream — the submit page bound should "
                    "make this impossible")
            if self._spec_mode == "draft":
                # Draft writes stop at pos + K - 1 <= cover mid-stream;
                # near the remaining-budget end any overshoot routes to
                # scratch inside the program (quality-only, never
                # correctness), so target cover is enough here too.
                if not self._ensure_draft_pages(req, cover):
                    raise RuntimeError(
                        "page pool exhausted for draft KV at a "
                        "speculative tick — the admission gate's draft "
                        "factor should make this impossible")
        live = [(s, r) for s, r in live
                if r.status is RequestStatus.RUNNING]
        if not live:
            return None
        mask = np.zeros((self.max_slots,), bool)
        remaining = np.ones((self.max_slots,), np.int32)
        for slot, req in live:
            mask[slot] = True
            remaining[slot] = max(req.max_new_tokens - len(req.tokens), 1)
        bank = ((self._adapters.stacks,)
                if self._adapters is not None else ())
        lookup_hits = 0
        t0 = time.monotonic()
        if self._spec_mode == "lookup":
            proposals = np.zeros((self.max_slots, K), np.int32)
            for slot, req in live:
                proposals[slot], hit = self._lookup_proposals(req)
                lookup_hits += int(hit)
            self._state, emit, ns = self._spec(
                self.params, self._state, jnp.asarray(mask),
                self._table.copy(), remaining, proposals, *bank)
        else:
            self._state, emit, ns = self._spec(
                self.params, self._draft_params, self._state,
                jnp.asarray(mask), self._table.copy(), self._dtable.copy(),
                remaining, *bank)
        return _TickFlight(
            entries=[(slot, req, req._preempted) for slot, req in live],
            t_dispatch=t0, emit=emit, ns=ns, lookup_hits=lookup_hits)

    def _lookup_proposals(self, req: Request):
        """Prompt-lookup drafting: propose the ``K`` tokens that followed
        the most recent earlier occurrence of the stream's last ``n``
        tokens (prompt + committed output), no draft model involved. On a
        miss the proposal is the last token repeated — a deliberately weak
        draft that still verifies correctly, so a miss costs acceptance
        rate, never exactness. Returns ``(proposal[K] int32, hit bool)``.

        This is pure host work on a few-KiB token array per slot per
        tick; the device only ever sees the proposal as traced data."""
        K = self._spec_k
        n = self._spec_lookup
        seq = np.concatenate([
            np.asarray(req._serve_ids[0][:req._pos_base + 1], np.int32),
            np.asarray(req.tokens, np.int32)])
        if len(seq) > n:
            pattern = seq[-n:]
            windows = np.lib.stride_tricks.sliding_window_view(seq[:-1], n)
            hits = np.nonzero((windows == pattern).all(axis=1))[0]
            if hits.size:
                start = int(hits[-1]) + n
                prop = seq[start:start + K]
                if prop.size < K:
                    prop = np.concatenate(
                        [prop, np.full((K - prop.size,), seq[-1],
                                       np.int32)])
                return prop, True
        return np.full((K,), seq[-1], np.int32), False

    def _commit_token(self, req: Request, token: int) -> bool:
        """Append + stream one token. With an emitter (async mode) the
        callback is QUEUED, not run — the tick loop never waits on a
        consumer — and a callback that already raised off-thread fails
        the request here, before committing more. Inline mode (sync A/B)
        keeps the original semantics: a raising ``on_token`` fails ONLY
        its own request (slot freed, batch untouched). Returns False when
        the request was retired instead of committed to."""
        if req._emit_error is not None:
            self._retire(req, RequestStatus.FAILED, req._emit_error)
            return False
        req.tokens.append(token)
        if req.on_token is not None:
            if self._emitter is not None:
                self._emitter.put(req, token)
            else:
                try:
                    req.on_token(token)
                except Exception as e:
                    self._retire(req, RequestStatus.FAILED, e)
                    return False
        return True

    def _finish_req(self, req: Request, status: RequestStatus,
                    error: Optional[BaseException] = None):
        """Terminal transition, emitter-aware: status/error land NOW (the
        engine thread's scheduling view stays consistent), while for a
        streaming request in async mode the observable completion
        (``_done``, ``_on_finish``) is queued BEHIND its buffered tokens
        — the drain-on-retire barrier that keeps ``result()`` ordered
        after the last ``on_token`` call and lets shutdown/failover drain
        instead of drop."""
        if self._emitter is not None and req.on_token is not None:
            if req._finish(status, error, defer=True):
                self._emitter.finish(req)
        else:
            req._finish(status, error)

    def _retire(self, req: Request, status: RequestStatus,
                error: Optional[BaseException] = None):
        if req.slot is not None:
            if self._paged:
                self._release_slot_pages(req.slot)
            self._slots.release(req.slot)
        if req._adapter_pinned:
            req._adapter_pinned = False
            self._adapters.release(req.adapter)
        if req.adapter is not None:
            self._stats.record_adapter_tokens(req.adapter, len(req.tokens))
        if req.priority is not None:
            self._stats.record_priority_tokens(req.priority, len(req.tokens))
        self._finish_req(req, status, error)
        self._stats.record_finish(req.status)
        retire_args = {"status": req.status.value, "tokens": len(req.tokens)}
        if req.priority is not None:
            retire_args["priority"] = req.priority
        self._tracer.instant("retire", trace_id=req.trace_id,
                             args=retire_args)
        if req.status is RequestStatus.FAILED and error is not self._error:
            # Engine-fatal retirements are already covered by the single
            # "fatal" event; request-level failures get their own.
            self._flight.record("request_failed", trace_id=req.trace_id,
                                error=repr(error))
