"""Continuous-batching serving engine over the compiled generation stack.

The TPU constraint (GSPMD: peak performance comes from a small number of
fixed-shape compiled programs) shapes the whole design. The engine owns a
fixed ``[max_slots, max_len]`` decode state — per-slot KV cache, write
position, carry rng, and eos latch — and after warmup runs exactly TWO
compiled programs, no matter how requests arrive or leave:

* ``prefill_into_slot`` — one compiled executable per 128-bucketed prompt
  length (:func:`generation._bucket128`); the prompt is EDGE-padded on the
  host (numpy, so no per-length jnp pad programs) and the executable reads
  logits at the traced ``true_len - 1``, builds a fresh batch-1 cache, and
  writes the whole slot state with ``dynamic_update_slice`` at the traced
  slot index.
* ``decode_step_all_slots`` — one token for every slot per tick, a
  ``jax.vmap`` of the batch-1 single-token forward over the slot axis,
  sharing :func:`generation._next_token` with the offline scan so engine
  streams are bit-identical to offline :func:`generation.generate` for the
  same (prompt, rng, sampling). Slot membership is a host-provided boolean
  mask ARGUMENT, never a shape: admitting or retiring a request changes
  the mask bits, not the program.

Around the two programs: a bounded FCFS admission queue with backpressure,
per-request ``max_new_tokens``/timeout/cancellation, streaming token
callbacks, error isolation (a failing callback frees its slot without
touching the rest of the batch), and a graceful drain on shutdown that
cooperates with ``Accelerator.install_preemption_handler()`` — on
preemption the engine stops admitting, finishes in-flight requests, and
cancels the queue, so the process can exit inside the notice window.

Pad-KV safety is the same argument as the offline path: the prompt is
edge-padded to bucket P, prefill writes KV for positions [0, P), but the
decode mask attends ``k_pos <= q_pos`` and every decode write lands at the
current position *before* any query that could see it — pad entries past
``true_len`` are overwritten at-or-before the first query that could
attend them.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..generation import (
    _bucket128,
    _check_position_bound,
    _make_selector,
    _next_token,
)
from ..inference import resolve_model_source
from .metrics import ServingStats
from .request import Request, RequestStatus
from .scheduler import AdmissionQueue, QueueFull, SlotScheduler

__all__ = ["ServingEngine"]


class ServingEngine:
    """Slot-based continuous-batching decode service.

    Args:
      model: an accelerate_tpu ``Model``/``AcceleratedModel`` or a bare
        cache-threading flax module (see ``generation.supports_kv_cache``).
      params: parameter pytree (defaults to the prepared model's).
      max_slots: decode lanes — the fixed batch dimension of the tick.
      max_len: per-slot KV capacity; every request must satisfy
        ``prompt_len + max_new_tokens <= max_len``.
      eos_token_id / do_sample / temperature / top_k / top_p: ENGINE-level
        sampling config — baked into the two executables (a per-request
        change would be a recompile). Greedy when ``do_sample=False``.
      cache_dtype: KV buffer dtype (default bfloat16, like offline).
      max_queued: admission-queue bound (backpressure past it).
      accelerator: optional — wires preemption-drain cooperation and, when
        the accelerator carries a ``serving_stats``, shares it so
        ``Accelerator.log(include_serving=True)`` sees this engine.
      autostart: spawn the engine thread (and warm up) in the constructor.
      warmup: run dummy requests through both programs at start so the
        first real request never pays a compile; stats reset afterwards.
    """

    def __init__(self, model, params=None, *, max_slots: int = 4,
                 max_len: int = 256, eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 top_k: Optional[int] = None, top_p: Optional[float] = None,
                 cache_dtype=None, max_queued: int = 64, accelerator=None,
                 stats: Optional[ServingStats] = None, autostart: bool = True,
                 warmup: bool = True, idle_poll_s: float = 0.005):
        from ..big_modeling import cache_factory_for

        module, _, params, mesh, _ = resolve_model_source(
            model, params=params, accelerator=accelerator)
        if params is None:
            raise ValueError("ServingEngine needs params (pass params= or a "
                             "prepared Model)")
        if module is None or hasattr(module, "init_decode_cache"):
            raise NotImplementedError(
                "ServingEngine serves decoder-only cache-threading modules; "
                "encoder-decoder models go through seq2seq_generate")
        factory = cache_factory_for(module)
        if factory is None:
            raise TypeError(
                f"{type(module).__name__} does not thread a KV cache "
                "(big_modeling.cache_factory_for) — the engine cannot hold "
                "its decode state")
        if max_slots < 1 or max_len < 2:
            raise ValueError(f"need max_slots >= 1 and max_len >= 2 "
                             f"(got {max_slots}, {max_len})")

        self.module = module
        self.params = params
        self.mesh = mesh
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.eos_token_id = eos_token_id
        self._dtype = cache_dtype or jnp.bfloat16
        self._factory = factory
        self._sampling = (float(temperature), top_k, top_p) if do_sample else None
        self._select = _make_selector(self._sampling)
        self._idle_poll_s = float(idle_poll_s)
        self._accelerator = accelerator

        # One slot's cache, used as the state template. Ring (sliding-window)
        # caches rotate by stored position — the slot-stacked
        # dynamic_update_slice layout below does not model that, so refuse
        # loudly rather than serve corrupted windows.
        slot_cache = factory(1, self.max_len, self._dtype)
        if any(isinstance(layer, dict) and "pos" in layer for layer in slot_cache):
            raise NotImplementedError(
                "sliding-window (ring) KV caches are not supported by the "
                "serving engine yet; set the config's window >= max_len")

        self._state = {
            "cache": jax.tree.map(
                lambda l: jnp.zeros((self.max_slots,) + l.shape, l.dtype),
                slot_cache),
            "pos": jnp.zeros((self.max_slots,), jnp.int32),
            "tok": jnp.zeros((self.max_slots,), jnp.int32),
            "rng": jnp.zeros((self.max_slots, 2), jnp.uint32),
            "done": jnp.zeros((self.max_slots,), bool),
        }

        # CPU jit warns (and ignores) donation; donate only where it works.
        donate = () if jax.default_backend() == "cpu" else (1,)
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=donate)
        self._decode = jax.jit(self._decode_fn, donate_argnums=donate)

        if stats is None and accelerator is not None:
            stats = getattr(accelerator, "serving_stats", None)
        self._stats = stats if stats is not None else ServingStats()
        self._queue = AdmissionQueue(max_queued)
        self._slots = SlotScheduler(self.max_slots)

        self._accepting = False
        self._stop = False          # hard stop: cancel everything, exit now
        self._drain = False         # finish all accepted work, then exit
        self._abort_queue = False   # preemption: finish running, cancel queued
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None
        self._warmup_on_start = bool(warmup)
        if autostart:
            self.start()

    # ------------------------------------------------------------------
    # the two compiled programs
    # ------------------------------------------------------------------
    def _prefill_fn(self, params, state, ids_p, slot, rng, true_len):
        """ids_p [1, P] edge-padded prompt; slot/true_len traced i32 scalars.
        Builds a fresh batch-1 cache, runs the prompt, selects the first
        token exactly like offline generate (rng split into carry + prefill
        halves, selection at ``true_len - 1``), and writes the slot's whole
        decode state at the traced slot index. Returns (state, first_token).
        """
        cache = self._factory(1, self.max_len, self._dtype)
        logits, cache = self.module.apply(
            {"params": params}, ids_p, cache=cache, cache_pos=0)
        rng_carry, pre_rng = jax.random.split(rng)
        last = jax.lax.dynamic_slice_in_dim(logits, true_len - 1, 1, axis=1)[:, 0]
        seen = jnp.zeros((1, 1), bool)
        tok, done = _next_token(last, pre_rng, seen, jnp.zeros((1,), bool),
                                self._select, self.eos_token_id, ids_p.dtype)
        new_cache = jax.tree.map(
            lambda full, one: jax.lax.dynamic_update_slice(
                full, one[None].astype(full.dtype), (slot,) + (0,) * one.ndim),
            state["cache"], cache)
        state = {
            "cache": new_cache,
            "pos": state["pos"].at[slot].set(true_len),
            "tok": state["tok"].at[slot].set(tok[0].astype(jnp.int32)),
            "rng": state["rng"].at[slot].set(rng_carry),
            "done": state["done"].at[slot].set(done[0]),
        }
        return state, tok[0]

    def _decode_fn(self, params, state, active):
        """One tick: a batch-1 single-token forward vmapped over the slot
        axis (per-slot scalar cache_pos, per-slot rng chain — bitwise the
        same selection as offline's scan body). The cache commits
        unconditionally (an inactive slot rewrites its frozen position with
        garbage nobody will read — its next use starts with a fresh prefill)
        but pos/tok/rng/done advance only where ``active`` is set, so
        retired slots stay frozen and in-bounds. Returns
        (state, tokens [S], done [S])."""

        def one_slot(cache, tok, pos, rng, done):
            logits, cache = self.module.apply(
                {"params": params}, tok[None, None], cache=cache, cache_pos=pos)
            rng, sub = jax.random.split(rng)
            nxt, done = _next_token(logits[:, -1], sub, jnp.zeros((1, 1), bool),
                                    done[None], self._select, self.eos_token_id,
                                    tok.dtype)
            return cache, nxt[0], rng, done[0]

        new_cache, toks, rngs, dones = jax.vmap(one_slot)(
            state["cache"], state["tok"], state["pos"], state["rng"],
            state["done"])
        state = {
            "cache": new_cache,
            "pos": jnp.where(active, state["pos"] + 1, state["pos"]),
            "tok": jnp.where(active, toks, state["tok"]),
            "rng": jnp.where(active[:, None], rngs, state["rng"]),
            "done": jnp.where(active, dones, state["done"]),
        }
        return state, toks, dones

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    def start(self):
        """Spawn the engine thread (idempotent) and run warmup traffic."""
        if self._thread is not None:
            return
        self._accepting = True
        self._thread = threading.Thread(target=self._run,
                                        name="serving-engine", daemon=True)
        self._thread.start()
        if self._warmup_on_start:
            self.warmup()

    def warmup(self, timeout: float = 120.0):
        """Compile both programs by pushing dummy requests through the
        normal path: the smallest prompt bucket (prefill) and one decode
        tick. ``ignore_eos`` keeps the dummy decoding even if the model
        emits eos immediately. Counters reset afterwards so warmup traffic
        never pollutes serving metrics."""
        req = self.submit(np.zeros((1, 1), np.int32), max_new_tokens=2,
                          seed=0, ignore_eos=True, block=True)
        if not req.wait(timeout):
            raise TimeoutError("engine warmup did not finish "
                               f"within {timeout}s")
        self._raise_if_failed(req)
        self._stats.reset()

    @staticmethod
    def _raise_if_failed(req):
        if req.status != RequestStatus.COMPLETED:
            raise RuntimeError(f"warmup request {req.status.value}") from req.error

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Stop the engine. ``drain=True`` finishes every accepted request
        (queued and running) first; ``drain=False`` cancels them. Either
        way, blocks for the engine thread (up to ``timeout``) and then
        drains in-flight async checkpoint saves — a serving process is
        often the same process that just trained the weights it serves,
        and exiting with Orbax writes still in flight drops them."""
        from .. import checkpointing

        self._accepting = False
        if drain:
            self._drain = True
        else:
            self._stop = True
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        checkpointing.wait_for_saves()
        if self._error is not None:
            raise RuntimeError("serving engine died") from self._error

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    @property
    def running(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # ------------------------------------------------------------------
    # submission
    # ------------------------------------------------------------------
    def submit(self, prompt_ids=None, *, request: Optional[Request] = None,
               max_new_tokens: int = 20, seed: Optional[int] = None,
               rng=None, timeout: Optional[float] = None, on_token=None,
               ignore_eos: bool = False, block: bool = False,
               block_timeout: Optional[float] = None) -> Request:
        """Enqueue one request; returns its :class:`Request` handle
        immediately. Raises :class:`scheduler.QueueFull` under backpressure
        when ``block=False``; with ``block=True`` the caller waits for
        queue space instead (up to ``block_timeout``)."""
        if request is None:
            request = Request(prompt_ids, max_new_tokens=max_new_tokens,
                              rng=rng, seed=seed, timeout=timeout,
                              on_token=on_token, ignore_eos=ignore_eos)
        if not self._accepting or self._stop or self._drain:
            raise RuntimeError("serving engine is not accepting requests "
                               "(not started, shutting down, or preempted)")
        S = request.prompt_ids.shape[1]
        if S < 1:
            raise ValueError("empty prompt")
        if S + request.max_new_tokens > self.max_len:
            raise ValueError(
                f"prompt ({S}) + max_new_tokens ({request.max_new_tokens}) "
                f"exceeds the engine's max_len ({self.max_len}); resize the "
                "engine or shorten the request")
        _check_position_bound(self.module, S + request.max_new_tokens)
        request.submitted_at = time.monotonic()
        try:
            self._queue.put(request, block=block, timeout=block_timeout)
        except QueueFull:
            self._stats.record_reject()
            raise
        self._stats.record_submit(len(self._queue))
        return request

    def serving_metrics(self) -> dict:
        """Scalar snapshot of the engine's counters (see
        :class:`metrics.ServingStats.summary`)."""
        return self._stats.summary()

    @property
    def stats(self) -> ServingStats:
        return self._stats

    # ------------------------------------------------------------------
    # engine thread
    # ------------------------------------------------------------------
    def _run(self):
        try:
            while not self._stop:
                if (self._accelerator is not None
                        and getattr(self._accelerator, "preemption_requested", False)
                        and not (self._drain or self._abort_queue)):
                    # Preemption drain: stop admitting, let in-flight
                    # requests finish, cancel the queue — the notice window
                    # is for flushing work, not for taking more.
                    self._accepting = False
                    self._abort_queue = True
                now = time.monotonic()
                for _, req in self._slots.active():
                    if req.cancel_requested:
                        self._retire(req, RequestStatus.CANCELLED)
                    elif req._deadline_passed(now):
                        self._retire(req, RequestStatus.TIMED_OUT)
                if self._abort_queue:
                    for req in self._queue.drain():
                        req._finish(RequestStatus.CANCELLED)
                        self._stats.record_finish(req.status)
                while self._slots.has_free():
                    req = self._queue.get_nowait()
                    if req is None:
                        break
                    if req.cancel_requested:
                        req._finish(RequestStatus.CANCELLED)
                        self._stats.record_finish(req.status)
                    elif req._deadline_passed(now):
                        req._finish(RequestStatus.TIMED_OUT)
                        self._stats.record_finish(req.status)
                    else:
                        self._admit(req)
                if self._slots.active_slots:
                    self._tick()
                elif self._drain and not len(self._queue):
                    break
                elif self._abort_queue:
                    break
                else:
                    # Idle: block briefly on the queue so a submit wakes the
                    # loop without a hot spin; the request is re-checked and
                    # admitted on the next pass.
                    req = self._queue.get(timeout=self._idle_poll_s)
                    if req is not None:
                        self._admit(req)
        except BaseException as e:  # engine-fatal: fail everything loudly
            self._error = e
        finally:
            self._accepting = False
            terminal = (RequestStatus.FAILED if self._error is not None
                        else RequestStatus.CANCELLED)
            for _, req in list(self._slots.active()):
                self._retire(req, terminal, self._error)
            for req in self._queue.drain():
                req._finish(terminal, self._error)
                self._stats.record_finish(req.status)

    def _admit(self, req: Request):
        """Prefill ``req`` into a free slot: host edge-pad to the 128
        bucket (numpy — a jnp pad would compile per prompt length), run
        ``prefill_into_slot``, and commit the first token. TTFT is stamped
        here because prefill itself emits token #1."""
        req.admitted_at = time.monotonic()
        slot = self._slots.assign(req)
        S = req.prompt_ids.shape[1]
        P = self._bucket(S)
        ids_p = req.prompt_ids
        if P > S:
            ids_p = np.pad(ids_p, ((0, 0), (0, P - S)), mode="edge")
        rng = req.rng if req.rng is not None else jax.random.PRNGKey(
            req.seed if req.seed is not None else 0)
        self._state, tok = self._prefill(
            self.params, self._state, ids_p, np.int32(slot), rng, np.int32(S))
        token = int(tok)
        req.status = RequestStatus.RUNNING
        now = time.monotonic()
        req.first_token_at = now
        self._stats.record_admit(
            queue_wait_ms=(req.admitted_at - req.submitted_at) * 1e3,
            ttft_ms=(now - req.submitted_at) * 1e3)
        if self._commit_token(req, token):
            if (len(req.tokens) >= req.max_new_tokens
                    or (not req.ignore_eos and self.eos_token_id is not None
                        and token == self.eos_token_id)):
                self._retire(req, RequestStatus.COMPLETED)

    def _bucket(self, S: int) -> int:
        P = min(_bucket128(S), self.max_len)
        bound = getattr(getattr(self.module, "config", None),
                        "max_position_embeddings", None)
        if bound is not None:
            P = min(P, int(bound))
        return max(P, S)

    def _tick(self):
        """One ``decode_step_all_slots`` execution + host commit/retire."""
        mask = np.zeros((self.max_slots,), bool)
        occupants = self._slots.active()
        for slot, _ in occupants:
            mask[slot] = True
        t0 = time.monotonic()
        self._state, toks, dones = self._decode(
            self.params, self._state, jnp.asarray(mask))
        toks = np.asarray(toks)     # sync point: the tick's device work
        dones = np.asarray(dones)
        dt = time.monotonic() - t0
        committed = 0
        for slot, req in occupants:
            if not self._commit_token(req, int(toks[slot])):
                continue  # callback failed; slot already freed
            committed += 1
            if (len(req.tokens) >= req.max_new_tokens
                    or (not req.ignore_eos and bool(dones[slot]))):
                self._retire(req, RequestStatus.COMPLETED)
        self._stats.record_tick(active_slots=len(occupants),
                                committed_tokens=committed,
                                max_slots=self.max_slots, seconds=dt)

    def _commit_token(self, req: Request, token: int) -> bool:
        """Append + stream one token. A raising ``on_token`` callback fails
        ONLY its own request (slot freed, batch untouched); returns False
        in that case."""
        req.tokens.append(token)
        if req.on_token is not None:
            try:
                req.on_token(token)
            except Exception as e:
                self._retire(req, RequestStatus.FAILED, e)
                return False
        return True

    def _retire(self, req: Request, status: RequestStatus,
                error: Optional[BaseException] = None):
        if req.slot is not None:
            self._slots.release(req.slot)
        req._finish(status, error)
        self._stats.record_finish(req.status)
