"""Request objects for the serving engine.

A :class:`Request` is both the admission record the engine schedules and
the HANDLE the caller keeps: ``submit()`` returns it immediately, tokens
stream into it (and through ``on_token``) as they are committed, and
``result()`` blocks until the request retires. All mutation after submit
happens on the engine thread; the caller only reads, waits, or flips the
cancel flag — so the only synchronization needed is the done event and a
couple of volatile flags.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Optional

import numpy as np


class RequestStatus(enum.Enum):
    QUEUED = "queued"        # submitted, waiting for a slot
    PREFILLING = "prefilling"  # holds a slot; prompt chunks still running
    RUNNING = "running"      # prefilled into a slot, decoding
    COMPLETED = "completed"  # emitted eos or max_new_tokens
    FAILED = "failed"        # admission/callback error (slot freed, batch unharmed)
    CANCELLED = "cancelled"  # cancel() honored (or engine shutdown without drain)
    TIMED_OUT = "timed_out"  # per-request deadline passed while queued or running


_TERMINAL = (RequestStatus.COMPLETED, RequestStatus.FAILED,
             RequestStatus.CANCELLED, RequestStatus.TIMED_OUT)


class Request:
    """One generation request: prompt + per-request knobs + result handle.

    Sampling parameters (greedy vs temperature/top-k/top-p) and the eos id
    are ENGINE-level — they are baked into the two compiled programs, so a
    per-request change would mean a recompile; what varies per request is
    everything host-side: ``max_new_tokens``, ``timeout``, the rng key, the
    streaming callback, and cancellation.
    """

    def __init__(self, prompt_ids, max_new_tokens: int = 20,
                 rng=None, seed: Optional[int] = None,
                 timeout: Optional[float] = None,
                 on_token: Optional[Callable[[int], None]] = None,
                 ignore_eos: bool = False,
                 adapter: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 priority: Optional[str] = None):
        ids = np.asarray(prompt_ids, np.int32)
        if ids.ndim == 1:
            ids = ids[None, :]
        if ids.ndim != 2 or ids.shape[0] != 1:
            raise ValueError(
                f"prompt_ids must be [S] or [1, S] (got shape {ids.shape}); "
                "the engine schedules requests individually into slots")
        self.prompt_ids = ids
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens must be >= 1 (got {max_new_tokens})")
        self.rng = rng
        self.seed = seed
        self.timeout = timeout
        self.on_token = on_token
        #: run to exactly max_new_tokens even if eos is emitted (warmup and
        #: benchmark traffic — keeps tick counts deterministic).
        self.ignore_eos = ignore_eos
        if adapter is not None and (not isinstance(adapter, str) or not adapter):
            raise ValueError(
                f"adapter must be a non-empty string or None (got {adapter!r})")
        #: named LoRA adapter this request decodes under (None = base model).
        self.adapter = adapter
        if trace_id is not None and (not isinstance(trace_id, str) or not trace_id):
            raise ValueError(
                f"trace_id must be a non-empty string or None (got {trace_id!r})")
        #: correlation id carried through every lifecycle edge (gateway-minted
        #: or client-supplied); engine spans and the SSE done-summary tag it.
        self.trace_id = trace_id
        if priority is not None and (not isinstance(priority, str)
                                     or not priority):
            raise ValueError(
                f"priority must be a non-empty string or None (got {priority!r})")
        #: client-declared traffic class (e.g. ``"interactive"``/``"batch"``).
        #: With the engine's default :class:`~.control.PriorityPolicy` this
        #: is ACTED ON: admission is a priority queue (FIFO within class)
        #: and pool-exhaustion preemption evicts the lowest class first.
        #: It also labels tracer spans and per-priority metrics series.
        #: Engines built with ``priority_policy=None`` fall back to the
        #: historical measurement-only FCFS behaviour.
        self.priority = priority

        self.tokens: list[int] = []        # committed tokens, streamed order
        self.status = RequestStatus.QUEUED
        self.error: Optional[BaseException] = None
        self.slot: Optional[int] = None

        self.submitted_at: Optional[float] = None   # engine-stamped (monotonic)
        self.admitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None

        self._cancel_requested = False
        self._done = threading.Event()
        # True once the terminal transition ran (engine thread). Under the
        # async host runtime the OBSERVABLE completion (``_done`` /
        # ``_on_finish``) may lag this flag: the engine sets status/error
        # synchronously via ``_finish(..., defer=True)`` and the emitter
        # thread calls ``_complete()`` only after every buffered ``on_token``
        # callback for this request has drained — the drain-on-retire
        # barrier that keeps ``result()`` ordered after the last callback.
        self._finished = False
        # Off-thread emission bookkeeping (engine + emitter threads; the
        # int is GIL-atomic enough for flow control): callbacks queued but
        # not yet run, and the first exception an ``on_token`` raised on
        # the emitter thread (the engine's loop-top sweep retires on it).
        self._emit_pending = 0
        self._emit_error: Optional[BaseException] = None
        # Internal completion hook (router layer): called ON THE ENGINE
        # THREAD exactly once, right after the terminal transition — the
        # ReplicaSet uses it to fail a dead replica's in-flight requests
        # over to a healthy one without polling.
        self._on_finish: Optional[Callable[["Request"], None]] = None

        # Chunked-prefill bookkeeping (engine thread only): the per-request
        # rng key is fixed at admission because every chunk call replays the
        # same split; ``_next_chunk`` is the prefill frontier in chunk units.
        self._rng_key = None
        self._next_chunk = 0
        self._chunks_total = 0
        self._chunk_keys: Optional[list] = None

        # Adapter bookkeeping (engine thread only): the bank row this
        # request gathers, and whether it holds a residency pin that
        # _retire must release.
        self._adapter_row = 0
        self._adapter_pinned = False

        # Paged-engine bookkeeping (engine thread only). ``_serve_ids`` is
        # the token sequence admission actually prefills — the prompt, or
        # prompt + tokens-emitted-so-far after a pool-exhaustion
        # preemption (the same resume-as-longer-prompt trick the router's
        # failover uses: for greedy decoding the resumed prefill's
        # first-token pick IS the interrupted decode step, bit-exact).
        self._serve_ids = None
        self._preempted = 0  # times evicted by pool exhaustion
        # Host mirror of the device write position: after prefill the
        # engine sets this so that ``_pos_base + len(tokens)`` is always
        # the slot's next KV write position (page-coverage checks).
        self._pos_base = 0
        # Lowest table index that may still be live: sliding-window page
        # freeing advances it so re-coverage never re-allocates pages the
        # window already retired (reset to 0 on every (re)admission).
        self._page_floor = 0

    # -- caller API -----------------------------------------------------
    def cancel(self):
        """Request cancellation: a queued request is dropped before it ever
        takes a slot; a prefilling or running request retires at the next
        scheduler pass (its slot frees without disturbing the rest of the
        batch, and no further prefill chunks are spent on it)."""
        self._cancel_requested = True

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until the request retires; True if it did within timeout."""
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Generated token ids [n] (prompt excluded), blocking until done.

        Raises ``TimeoutError`` if the wait times out, or ``RuntimeError``
        (chaining the recorded error, if any) when the request did not
        complete — failed, cancelled, or deadline-expired.
        """
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self.status != RequestStatus.COMPLETED:
            raise RuntimeError(
                f"request {self.status.value}"
                + (f": {self.error}" if self.error is not None else "")
            ) from self.error
        return np.asarray(self.tokens, np.int32)

    def output_ids(self, timeout: Optional[float] = None) -> np.ndarray:
        """[1, S + n] prompt + completion — the offline ``generate`` shape."""
        toks = self.result(timeout)
        return np.concatenate([self.prompt_ids, toks[None, :]], axis=1)

    # -- engine internals ----------------------------------------------
    def _deadline_passed(self, now: Optional[float] = None) -> bool:
        if self.timeout is None or self.submitted_at is None:
            return False
        return (now if now is not None else time.monotonic()) \
            > self.submitted_at + self.timeout

    def _finish(self, status: RequestStatus, error: Optional[BaseException] = None,
                defer: bool = False):
        """Terminal transition. ``defer=True`` (async engines, streaming
        requests) records status/error immediately — so the engine thread
        sees a consistent terminal state for scheduling — but leaves the
        observable completion (:meth:`_complete`) to the emitter thread,
        AFTER this request's buffered callbacks drain. Returns True when
        this call performed the transition (callers that defer must queue
        the completion exactly once)."""
        if self._finished:  # first terminal transition wins
            return False
        self._finished = True
        self.status = status
        self.error = error
        if not defer:
            self._complete()
        return True

    def _complete(self):
        """Second half of the terminal transition: stamp, wake waiters,
        fire the router hook. Runs on the engine thread (sync path) or the
        emitter thread (deferred path) — exactly once either way."""
        self.finished_at = time.monotonic()
        self._done.set()
        if self._on_finish is not None:
            try:
                self._on_finish(self)
            except Exception:
                # The hook belongs to the router layer; a raising hook must
                # not take down the thread finishing the request.
                pass

    def __repr__(self):
        return (f"Request(S={self.prompt_ids.shape[1]}, "
                f"max_new={self.max_new_tokens}, status={self.status.value}, "
                f"tokens={len(self.tokens)})")
