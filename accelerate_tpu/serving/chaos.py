"""Deterministic fault injection for serving engines.

A :class:`ChaosSchedule` is a script of faults keyed on the engine's
**decode-tick counter** — the clock that advances with token progress —
so a fault fires at exactly the same point in the token stream on every
run, regardless of host speed. The engine applies the schedule at the
top of every run-loop iteration (``ServingEngine(chaos=...)``), which
gives three primitives:

* **kill** — at tick T, raise through the engine's existing fault
  injection (:meth:`~.engine.ServingEngine.kill`): the run loop dies
  through its normal fatal path, every in-flight and queued request is
  retired FAILED, and the router fails them over token-exact. The
  injected error is a :class:`ChaosKilled` so postmortems distinguish
  scripted deaths from real ones.
* **hang** — at tick T, freeze the engine's published heartbeat for a
  duration while the loop keeps serving. To a
  :class:`~.supervisor.FleetSupervisor` watchdog this is
  indistinguishable from a wedged compiled call (`engine.error` stays
  None, the heartbeat stalls), which is precisely the failure mode lazy
  health checks can never catch — the watchdog must fence on liveness
  alone.
* **slow** — between ticks T0 and T1, sleep ``delay_s`` per loop
  iteration: degraded-but-alive, the gray-failure mode that stresses
  deadline handling and drain-rate estimation without killing anything.
* **wedge** — at tick T, stall the engine INSIDE the next reconcile
  barrier of a DISPATCHED compiled call for ``duration_s``: unlike
  ``hang`` (which fakes a stall by freezing the published heartbeat
  while the loop serves on), a wedge genuinely stops the loop mid
  device-wait — the case the async runtime's one-tick-ahead dispatch
  makes interesting, because the heartbeat republished at the reconcile
  barrier is what keeps a watchdog's detection latency within
  ``hang_timeout_s`` there.

Schedules are engine-thread only once attached (the engine calls
:meth:`apply` from its run loop); build and attach them before
``start()``. One schedule drives one engine — faults carry fired-state.
"""

from __future__ import annotations

import time
from typing import Optional

__all__ = ["ChaosKilled", "ChaosSchedule"]


class ChaosKilled(RuntimeError):
    """The error a scripted :meth:`ChaosSchedule.kill` injects — lets
    tests and postmortems tell a chaos-harness death from a real one."""


class ChaosSchedule:
    """A deterministic script of engine faults keyed on decode ticks.

    Builder methods chain::

        chaos = ChaosSchedule().kill(at_tick=8)
        engine = ServingEngine(model, params, chaos=chaos)

        ChaosSchedule().hang(at_tick=5)            # until killed/fenced
        ChaosSchedule().hang(at_tick=5, duration_s=0.5)  # self-healing
        ChaosSchedule().slow(from_tick=2, until_tick=10, delay_s=0.01)

    ``at_tick`` compares against :attr:`~.engine.ServingEngine.
    decode_ticks` with ``>=``, so a fault scheduled past the stream's
    end simply never fires (and :meth:`fired` reports which did).
    """

    def __init__(self):
        self._events: list[dict] = []

    # -- builders --------------------------------------------------------
    def kill(self, at_tick: int,
             error: Optional[BaseException] = None) -> "ChaosSchedule":
        """Script a replica death at decode tick ``at_tick`` (routed
        through ``engine.kill`` → the normal engine-fatal path)."""
        self._events.append({"kind": "kill", "at": int(at_tick),
                             "error": error, "fired": False})
        return self

    def hang(self, at_tick: int,
             duration_s: Optional[float] = None) -> "ChaosSchedule":
        """Script a hang at decode tick ``at_tick``: the heartbeat
        freezes (``duration_s=None`` = forever, i.e. until a watchdog
        kills the engine) while the loop keeps serving."""
        self._events.append({"kind": "hang", "at": int(at_tick),
                             "duration_s": duration_s, "until": None,
                             "fired": False})
        return self

    def slow(self, from_tick: int, until_tick: int,
             delay_s: float) -> "ChaosSchedule":
        """Script degraded ticks: sleep ``delay_s`` per loop iteration
        while ``from_tick <= decode_ticks < until_tick``."""
        if until_tick <= from_tick:
            raise ValueError(f"until_tick must exceed from_tick "
                             f"(got {from_tick}..{until_tick})")
        self._events.append({"kind": "slow", "at": int(from_tick),
                             "until_tick": int(until_tick),
                             "delay_s": float(delay_s), "fired": False})
        return self

    def wedge(self, at_tick: int, duration_s: float) -> "ChaosSchedule":
        """Script a genuine stall: at decode tick ``at_tick`` the engine
        sleeps ``duration_s`` inside its next reconcile barrier — a
        dispatched compiled call that "never returns" for that long. The
        loop truly stops (no heartbeats, no commits), then resumes."""
        if duration_s <= 0:
            raise ValueError(f"duration_s must be > 0 (got {duration_s})")
        self._events.append({"kind": "wedge", "at": int(at_tick),
                             "duration_s": float(duration_s),
                             "fired": False})
        return self

    # -- introspection ---------------------------------------------------
    def fired(self) -> list[str]:
        """Kinds of the events that have fired, in script order."""
        return [e["kind"] for e in self._events if e["fired"]]

    def __repr__(self):
        parts = ", ".join(
            f"{e['kind']}@{e['at']}{'*' if e['fired'] else ''}"
            for e in self._events)
        return f"ChaosSchedule({parts})"

    # -- engine hook -----------------------------------------------------
    def apply(self, engine):
        """Run due events against ``engine``. Called by the engine's run
        loop every iteration, BEFORE it checks its fail injection — a
        scripted kill therefore takes effect the same iteration it
        fires."""
        ticks = engine.decode_ticks
        now = time.monotonic()
        for e in self._events:
            kind = e["kind"]
            if kind == "kill":
                if not e["fired"] and ticks >= e["at"]:
                    e["fired"] = True
                    err = e["error"] if e["error"] is not None else \
                        ChaosKilled(f"chaos: scripted kill at tick {ticks}")
                    engine._flight.record("chaos_kill", tick=ticks)
                    engine.kill(err)
            elif kind == "hang":
                if not e["fired"] and ticks >= e["at"]:
                    e["fired"] = True
                    e["until"] = (None if e["duration_s"] is None
                                  else now + e["duration_s"])
                    engine._heartbeat_frozen = True
                    engine._flight.record(
                        "chaos_hang", tick=ticks,
                        duration_s=e["duration_s"])
                elif (e["fired"] and e["until"] is not None
                        and now >= e["until"]):
                    e["until"] = None
                    engine._heartbeat_frozen = False
                    engine._flight.record("chaos_hang_end", tick=ticks)
            elif kind == "wedge":
                if not e["fired"] and ticks >= e["at"]:
                    e["fired"] = True
                    engine._wedge_s = e["duration_s"]
                    engine._flight.record("chaos_wedge", tick=ticks,
                                          duration_s=e["duration_s"])
            elif kind == "slow":
                if e["at"] <= ticks < e["until_tick"]:
                    if not e["fired"]:
                        e["fired"] = True
                        engine._flight.record(
                            "chaos_slow", tick=ticks,
                            delay_s=e["delay_s"],
                            until_tick=e["until_tick"])
                    time.sleep(e["delay_s"])
