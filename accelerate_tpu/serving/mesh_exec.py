"""Mesh-sliced tensor-parallel execution for the serving engine.

One serving replica stops being one chip and becomes one *slice*: a
disjoint group of ``tp`` devices carrying a tensor-parallel shard of the
params, the per-slot KV cache, and the LoRA adapter bank, behind the same
three warm executables. The design is pure GSPMD (PAPERS.md: sharding as
compiler annotations, not hand-written collectives) — nothing in the
engine's program *functions* changes; this module only decides WHERE every
array lives and re-jits the same functions with
``jax.jit(..., in_shardings=..., out_shardings=...)``:

* **Params** — the Megatron column/row layout from
  :mod:`accelerate_tpu.parallel.sharding` (the exact rules the training
  side already uses), so a model trained under ``tp=N`` serves under the
  same partitioning with zero re-derivation.
* **KV cache** — each slot's cache rows shard on the *heads* dimension
  (the first non-length feature axis divisible by ``tp``): attention is
  embarrassingly parallel over kv-heads, so prefill/decode run their
  per-head work locally and only the row-parallel output projection
  all-reduces, exactly like training TP.
* **AdapterBank** — each stacked LoRA leaf shards to match its base
  kernel's layout: column-parallel targets shard ``b`` on ``d_out``,
  row-parallel targets shard ``a`` on ``d_in``; ``scale`` replicates.
  Row writes (load/evict) stay a single compiled
  ``dynamic_update_slice`` per leaf, now writing into sharded stacks.
* **Slot membership, pos/tok/rng/done rows** — replicated DATA, same as
  single-chip: membership stays a traced argument, never a shape, so the
  zero-recompile discipline survives sharding unchanged.

The cross-slice story rides on the host: under a mesh, prefix-cache
blocks are ``device_get`` host arrays (chunk-aligned, exactly the
portable redistribution unit of "Memory-efficient array redistribution
through portable collective communication", PAPERS.md) — a block saved by
one slice restores into any other slice's shardings via the restore
program's ``in_shardings``, which is what makes a fleet-shared
:class:`~.scheduler.PrefixCache` and token-exact cross-slice failover
possible.

Entry points: :class:`SlicePlan` (carve ``jax.devices()`` into disjoint
``tp``-wide slices and build each slice's mesh) and :class:`SliceExec`
(derive every sharding and wrap the engine's program functions). The
engine's ``tp=`` / ``mesh=`` kwargs and ``ReplicaSet.from_mesh`` route
through here; see ``docs/usage_guides/serving.md``.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Optional, Sequence

__all__ = ["SlicePlan", "SliceExec", "validate_serving_mesh"]


def _non_tp_product(mesh) -> int:
    return math.prod(s for ax, s in mesh.shape.items() if ax != "tp")


def validate_serving_mesh(mesh):
    """A serving slice mesh is tensor-parallel only: every non-``tp`` axis
    must be trivial. dp-style replication belongs to :class:`ReplicaSet`
    (independent engines), not to one engine's mesh — a dp>1 engine mesh
    would silently waste chips decoding the same batch. Raises
    ``ValueError`` with the fix spelled out."""
    if "tp" not in mesh.shape:
        raise ValueError(
            f"serving mesh must carry a 'tp' axis (got axes {dict(mesh.shape)}); "
            "build it with SlicePlan.plan(tp=...) or MeshConfig(tp=...)")
    extra = _non_tp_product(mesh)
    if extra != 1:
        raise ValueError(
            "serving engine meshes are tensor-parallel only, but this mesh "
            f"has non-tp extent {extra} ({dict(mesh.shape)}). Use "
            "ReplicaSet.from_mesh(tp=..., num_slices=...) for data-parallel "
            "replicas — each replica is its own tp-only slice.")
    return mesh


@dataclass(frozen=True)
class SlicePlan:
    """Disjoint tensor-parallel device slices: ``slices[i]`` is the device
    tuple backing replica ``i``. Built by :meth:`plan`; each slice's
    :class:`~jax.sharding.Mesh` (canonical axis names, ``tp`` innermost,
    from :class:`~accelerate_tpu.parallel.mesh.MeshConfig`) comes from
    :meth:`build_mesh`."""

    tp: int
    slices: tuple

    @classmethod
    def plan(cls, tp: int, *, num_slices: Optional[int] = None,
             devices: Optional[Sequence] = None) -> "SlicePlan":
        """Carve ``devices`` (default ``jax.devices()``) into
        ``num_slices`` disjoint groups of ``tp`` consecutive devices
        (consecutive = ICI-adjacent under the topology-aware device order,
        so intra-slice collectives stay nearest-neighbor). ``num_slices``
        defaults to every full slice the device count affords."""
        import jax

        if tp < 1:
            raise ValueError(f"tp must be >= 1 (got {tp})")
        devices = list(devices if devices is not None else jax.devices())
        afford = len(devices) // tp
        if afford < 1:
            raise ValueError(
                f"tp={tp} needs at least {tp} devices (have {len(devices)})")
        n = afford if num_slices is None else int(num_slices)
        if n < 1 or n > afford:
            raise ValueError(
                f"num_slices={num_slices} out of range: {len(devices)} "
                f"devices afford at most {afford} slices of tp={tp}")
        groups = tuple(tuple(devices[i * tp:(i + 1) * tp]) for i in range(n))
        return cls(tp=tp, slices=groups)

    def __len__(self) -> int:
        return len(self.slices)

    def build_mesh(self, index: int):
        """The slice's tp-only mesh over the canonical logical axes (all
        axes present, non-tp sizes 1 — so every PartitionSpec in the
        framework can name any axis)."""
        from ..parallel.mesh import MeshConfig

        return MeshConfig(dp=1, tp=self.tp,
                          devices=self.slices[index]).build()

    def __repr__(self):
        ids = [[getattr(d, "id", d) for d in s] for s in self.slices]
        return f"SlicePlan(tp={self.tp}, slices={ids})"


class SliceExec:
    """Sharding derivation + program compilation for ONE slice.

    Owns the slice mesh and produces, for the engine's fixed state layout:

    * ``param_shardings(params)`` — TP PartitionSpecs via the training
      rules (:func:`~accelerate_tpu.parallel.sharding.infer_param_shardings`
      with a tp-size plugin).
    * ``state_shardings(state, cache_length_axes)`` — KV leaves sharded on
      their heads axis, every per-slot scalar row replicated.
    * ``block_shardings(...)`` / ``bank_shardings(bank)`` — the prefix-
      cache chunk block and stacked-LoRA layouts.
    * ``jit(fn, in_shardings, out_shardings, donate)`` — the thin
      ``jax.jit`` wrapper all three warm programs go through.

    Everything is computed once at engine construction; the per-call cost
    of the mesh path is zero beyond the collectives XLA schedules.
    """

    def __init__(self, mesh):
        validate_serving_mesh(mesh)
        self.mesh = mesh
        self.tp = int(mesh.shape["tp"])
        from jax.sharding import NamedSharding, PartitionSpec

        self._NS, self._P = NamedSharding, PartitionSpec
        #: replicated-over-the-slice placement (scalars, ids, masks, rng).
        self.replicated = NamedSharding(mesh, PartitionSpec())

    # -- params ----------------------------------------------------------
    def param_shardings(self, params):
        """NamedSharding pytree for the model params under this slice's
        ``tp`` axis — the same Megatron column/row rules training uses
        (``infer_param_shardings``), with FSDP off: a serving slice holds
        whole TP shards, resharding-on-load handles any training-time
        fsdp factor."""
        from ..parallel.sharding import infer_param_shardings
        from ..utils.dataclasses import TensorParallelPlugin

        return infer_param_shardings(
            params, self.mesh,
            tp_plugin=TensorParallelPlugin(tp_size=self.tp))

    # -- KV cache --------------------------------------------------------
    def heads_axis(self, template_shape: tuple, length_axis: int) -> Optional[int]:
        """The shard axis for one KV leaf, template-relative (the per-slot
        ``factory(1, max_len)`` leaf, e.g. ``[1, L, n_kv, hd]``): the
        first non-length axis of extent > 1 divisible by ``tp`` — kv-heads
        for every built-in family, head_dim as the fallback when GQA left
        too few kv-heads to split. None means the leaf replicates (and a
        tp slice buys no KV memory on it)."""
        if self.tp == 1:
            return None
        for ax, size in enumerate(template_shape):
            if ax == length_axis:
                continue
            if size > 1 and size % self.tp == 0:
                return ax
        return None

    def cache_leaf_shardings(self, template_leaves, length_axes,
                             with_slot_axis: bool):
        """Flat list of NamedShardings, one per KV leaf. ``template_leaves``
        are the per-slot cache leaves (``eval_shape`` structs are fine);
        ``with_slot_axis`` prepends the engine's ``[max_slots]`` dimension
        (replicated — slots are data-parallel rows of one slice's batch,
        never split across its chips)."""
        out = []
        for leaf, lax in zip(template_leaves, length_axes):
            ax = self.heads_axis(tuple(leaf.shape), lax)
            if ax is None:
                out.append(self.replicated)
                continue
            shift = 1 if with_slot_axis else 0
            spec = [None] * (len(leaf.shape) + shift)
            spec[ax + shift] = "tp"
            out.append(self._NS(self.mesh, self._P(*spec)))
        return out

    def state_shardings(self, state, template_leaves, length_axes):
        """Shardings pytree matching the engine state dict exactly: the
        KV subtree (dense ``cache`` or paged ``pool``) per-leaf
        heads-sharded, every other row (pos/tok/rng/done/adapter_idx — the
        membership-as-data arrays) replicated so host writes and mask
        flips stay collective-free. The paged pool reuses the slot-axis
        path unchanged: a pool leaf is ``[num_pages+1, P, heads, hd]``
        where a slot cache leaf is ``[max_slots, L, heads, hd]`` — the
        leading axis is just pages instead of slots (replicated either
        way; pages are data-parallel rows), and the heads axis sits at the
        same template-relative offset.

        A speculative engine's DRAFT page pool (``dpool``) deliberately
        lands in the replicated bucket with the scalar rows: the draft is
        small, its K-step scan is latency- not FLOP-bound, and keeping its
        params and KV whole on every chip means the draft scan runs with
        zero collectives — only the wide target verify pays (and benefits
        from) the tp sharding. This is the GSPMD composition the
        speculative ``_spec`` program relies on: replicated draft feeding
        a tp-sharded verify needs no new communication machinery.

        A QUANTIZED engine's per-page scale arrays (``pscale``/``dpscale``,
        ``[n_leaves, num_pages+1]`` f32) also fall through to the
        replicated bucket: one scalar per page is tiny, and replicating it
        lets the heads-sharded int8 page rows dequantize chip-locally —
        no code here needs to know the pool is quantized at all."""
        import jax

        kv_key = "pool" if "pool" in state else "cache"
        kv_sh = jax.tree.unflatten(
            jax.tree.structure(state[kv_key]),
            self.cache_leaf_shardings(template_leaves, length_axes,
                                      with_slot_axis=True))
        # Non-KV entries expand to a full subtree of replicated shardings
        # (not a prefix leaf): ``place`` tree-maps state against this
        # strictly, and the draft pool is a pytree, not a row.
        return {key: (kv_sh if key == kv_key
                      else jax.tree.map(lambda _: self.replicated,
                                        state[key]))
                for key in state}

    def block_shardings(self, cache_structure, template_leaves, length_axes):
        """Shardings for one prefix-cache chunk block (a per-slot cache
        slice of width C: same axes as the template, no slot axis)."""
        import jax

        return jax.tree.unflatten(
            cache_structure,
            self.cache_leaf_shardings(template_leaves, length_axes,
                                      with_slot_axis=False))

    # -- adapter bank ----------------------------------------------------
    def bank_shardings(self, bank):
        """Shardings pytree for ``bank.stacks``: each target module's
        stacked LoRA factors shard to MATCH the base kernel's Megatron
        layout (the same ``ShardingRules`` regexes) — column-parallel
        targets shard ``b``'s ``d_out``, row-parallel targets shard
        ``a``'s ``d_in``; everything else (and any non-divisible dim)
        replicates. The bank row axis (dim 0) is never split: a row write
        must stay one ``dynamic_update_slice`` per leaf."""
        import jax

        from ..adapters.lora import adapter_module_paths
        from ..parallel.sharding import ShardingRules

        rules = ShardingRules()
        shardings = jax.tree.map(lambda _: self.replicated, bank.stacks)
        for dotted in adapter_module_paths(bank.stacks):
            tp_dim = rules.tp_dim_for(dotted.replace(".", "/") + "/kernel")
            mod = _get_mod(bank.stacks, dotted)
            a_sh, b_sh = self.replicated, self.replicated
            if tp_dim == -1 and mod["b"].shape[2] % self.tp == 0:
                b_sh = self._NS(self.mesh, self._P(None, None, "tp"))
            elif tp_dim == -2 and mod["a"].shape[1] % self.tp == 0:
                a_sh = self._NS(self.mesh, self._P(None, "tp", None))
            tgt = _get_mod(shardings, dotted)
            tgt["a"], tgt["b"] = a_sh, b_sh
        return shardings

    # -- compilation -----------------------------------------------------
    def jit(self, fn, in_shardings, out_shardings, donate_argnums=()):
        """``jax.jit`` with this slice's placements — the only compile
        entry the mesh path uses, so every warm program records its
        shardings in one place. in_shardings entries may be pytree
        prefixes (a single NamedSharding covers a whole subtree)."""
        import jax

        return jax.jit(fn, in_shardings=in_shardings,
                       out_shardings=out_shardings,
                       donate_argnums=donate_argnums)

    def place(self, tree, shardings):
        """Initial distribution: ``device_put`` every leaf onto its
        sharding (reshards committed arrays — e.g. params prepared under
        a training fsdp x tp mesh land in this slice's serving layout)."""
        import jax

        return jax.tree.map(lambda x, s: jax.device_put(x, s),
                            tree, shardings)

    def per_chip_bytes(self, tree) -> int:
        """Largest per-device byte footprint of ``tree`` across the slice
        (max over shards per leaf — the HBM-planning number the per-chip
        KV math in docs/performance.md predicts)."""
        import jax

        total = 0
        for leaf in jax.tree.leaves(tree):
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                total += max(s.data.nbytes for s in shards)
            else:
                total += getattr(leaf, "nbytes", 0)
        return total


def _get_mod(tree, dotted: str):
    node = tree
    for part in dotted.split("."):
        node = node[part]
    return node
