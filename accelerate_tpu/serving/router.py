"""Multi-replica routing and fault-tolerant failover over serving engines.

One :class:`~.engine.ServingEngine` is both a capacity ceiling and a
single point of failure: its fixed ``[max_slots, max_len]`` decode state
bounds concurrency, and its single engine thread dying fails every
in-flight stream. The :class:`ReplicaSet` is the serving-side analogue of
data-parallel sharding over the device mesh — N independently compiled,
independently failing engine replicas behind one submit surface:

* **Routing** — least-loaded, cache-aware: a new request goes to the
  healthy replica with a free slot and the longest prefix-cache hit for
  its prompt, then most free decode slots (ties broken by total
  occupancy ``engine.load``, page headroom, then index). When the best
  replica's admission queue is full the next one is tried; only when
  EVERY healthy replica is saturated does the router surface
  :class:`~.scheduler.QueueFull` — the signal the gateway maps to
  HTTP 429.
* **Health** — per-replica :class:`ReplicaState`:
  HEALTHY (in rotation) → DRAINING (out of rotation, finishing its
  streams — operator-initiated via :meth:`ReplicaSet.drain_replica`) →
  FAILED (fenced). Health is refreshed lazily on every routing decision
  and metrics read — an engine whose run loop recorded a fatal error is
  demoted without any monitor thread. A
  :class:`~.supervisor.FleetSupervisor` layers ACTIVE health on top:
  heartbeat-watchdog fencing of hung (error-less) replicas, factory
  rebuilds of FAILED ones (RESTARTING → HEALTHY via
  :meth:`ReplicaSet.restart_replica`), and a circuit breaker parking a
  replica that keeps dying in CRASH_LOOP.
* **Failover** — a replica whose run loop raises fails every request it
  held (the engine's own cleanup path). The router hooks each request's
  terminal transition: when the cause of death was the ENGINE (not the
  request), the replica is fenced and the request is resubmitted to a
  healthy replica as ``prompt + tokens_emitted_so_far``, so the stream
  RESUMES — no token is re-emitted, none is lost. Re-prefilling the
  grown prompt is exactly the work the chunk-aligned prefix cache makes
  cheap. For greedy decoding the resumed stream is token-identical to an
  uninterrupted one (prefill's first-token selection at position
  ``len - 1`` is the same computation as the decode step there); sampled
  streams resume without duplicates or gaps but restart the rng chain at
  the failover point, so the continuation is a fresh draw.

The caller-facing handle is a :class:`FleetRequest`: it survives
failovers (accumulating tokens across however many inner
:class:`~.request.Request` flights it takes) while mirroring the Request
API — ``tokens``, ``wait``, ``result``, ``output_ids``, ``cancel``.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..observability import new_trace_id
from .engine import ServingEngine
from .metrics import ServingStats
from .request import Request, RequestStatus
from .scheduler import QueueFull

__all__ = ["ReplicaSet", "ReplicaState", "FleetRequest"]


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"         # in rotation, taking new requests
    DRAINING = "draining"       # out of rotation, finishing in-flight streams
    FAILED = "failed"           # fenced: run loop died or operator killed it
    RESTARTING = "restarting"   # fenced, replacement engine being built
    CRASH_LOOP = "crash_loop"   # circuit open: too many restarts in a window
    PARKED = "parked"           # scaled down: engine released, factory kept


class _Replica:
    """One engine plus its routing state (router internals). A PARKED
    replica holds NO engine (``engine is None``) — only its retained
    factory, from which :meth:`ReplicaSet.unpark_replica` rebuilds it."""

    def __init__(self, index: int, engine: Optional[ServingEngine]):
        self.index = index
        self.engine = engine
        self.state = ReplicaState.HEALTHY
        self.failures = 0  # requests this replica failed over FROM
        self.restarts = 0  # successful engine rebuilds (supervisor)

    def __repr__(self):
        free = self.engine.free_slots if self.engine is not None else "-"
        return (f"_Replica({self.index}, {self.state.value}, "
                f"free={free})")


class FleetRequest:
    """Router-level handle for one generation, stable across failovers.

    Tokens stream into :attr:`tokens` (and through ``on_token``) exactly
    once each, no matter how many replicas the request visits; the
    per-flight inner :class:`~.request.Request` objects are an
    implementation detail. The per-request deadline is GLOBAL — time
    spent on a replica that later died still counts against ``timeout``.
    """

    def __init__(self, prompt_ids, max_new_tokens: int = 20,
                 rng=None, seed: Optional[int] = None,
                 timeout: Optional[float] = None,
                 on_token: Optional[Callable[[int], None]] = None,
                 ignore_eos: bool = False,
                 adapter: Optional[str] = None,
                 trace_id: Optional[str] = None,
                 priority: Optional[str] = None):
        # Reuse Request's prompt validation (shape + max_new bounds +
        # adapter/trace id/priority form).
        proto = Request(prompt_ids, max_new_tokens=max_new_tokens,
                        adapter=adapter, trace_id=trace_id,
                        priority=priority)
        self.prompt_ids = proto.prompt_ids
        self.max_new_tokens = proto.max_new_tokens
        self.rng = rng
        self.seed = seed
        self.timeout = timeout
        self.on_token = on_token
        self.ignore_eos = ignore_eos
        #: named LoRA adapter, preserved across failovers (None = base).
        self.adapter = proto.adapter
        #: traffic class, preserved across failovers (acted on by each
        #: engine's priority policy: queue order + preemption victims).
        self.priority = proto.priority
        #: correlation id shared by every flight this request takes —
        #: minted here (when the gateway didn't) so the spans a failover
        #: leaves on replica A and the resumed spans on replica B carry
        #: the SAME id and merge into one timeline.
        self.trace_id = proto.trace_id or new_trace_id()

        self.tokens: list[int] = []
        self.status = RequestStatus.QUEUED
        self.error: Optional[BaseException] = None
        #: replica indices this request ran on, in order (one entry when no
        #: failover happened; the failover test asserts on its length).
        self.replica_trail: list[int] = []

        self.submitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None

        self._cancel_requested = False
        self._done = threading.Event()
        self._done_callbacks: list[Callable[["FleetRequest"], None]] = []
        self._lock = threading.Lock()
        self._inner: Optional[Request] = None
        #: the most recently BUILT inner flight — the only one whose
        #: tokens may reach :meth:`_emit_from`. Normally identical to
        #: ``_inner``; it diverges exactly when a hung engine was
        #: force-retired by the supervisor and later unwedged: its stale
        #: flight keeps committing tokens, and this guard is what keeps
        #: them out of a stream that already resumed elsewhere.
        self._flight: Optional[Request] = None

    # -- caller API (mirrors Request) -----------------------------------
    def cancel(self):
        """Cancel the current flight; honored at the owning engine's next
        scheduler pass, and suppresses any further failover."""
        self._cancel_requested = True
        with self._lock:
            inner = self._inner
        if inner is not None:
            inner.cancel()

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def failovers(self) -> int:
        """How many times this request was resubmitted after a replica
        died (0 for an uninterrupted stream)."""
        return max(0, len(self.replica_trail) - 1)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def add_done_callback(self, fn: Callable[["FleetRequest"], None]):
        """Call ``fn(self)`` exactly once when the request reaches a
        terminal status — immediately (on the caller's thread) if it is
        already done, otherwise from whichever engine/router thread drives
        the terminal transition. This is the completion signal an event-
        loop front end bridges onto (``loop.call_soon_threadsafe``)
        instead of parking a thread in :meth:`wait`; callbacks must not
        block. Exceptions propagate to the finishing thread, so keep the
        callback a pure notification."""
        with self._lock:
            if not self._done.is_set():
                self._done_callbacks.append(fn)
                return
        fn(self)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Generated token ids [n] (prompt excluded), blocking until done;
        same error contract as :meth:`Request.result`."""
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self.status != RequestStatus.COMPLETED:
            raise RuntimeError(
                f"request {self.status.value}"
                + (f": {self.error}" if self.error is not None else "")
            ) from self.error
        return np.asarray(self.tokens, np.int32)

    def output_ids(self, timeout: Optional[float] = None) -> np.ndarray:
        """[1, S + n] prompt + completion — the offline ``generate`` shape."""
        toks = self.result(timeout)
        return np.concatenate([self.prompt_ids, toks[None, :]], axis=1)

    # -- router internals ------------------------------------------------
    def _emit_from(self, inner: "Request", token: int):
        """Inner on_token trampoline: runs on whichever engine thread owns
        the current flight. Tokens from a STALE flight (an abandoned hung
        engine still committing after its requests were failed over) are
        dropped — exactly-once emission must hold across force-retires
        too. Callback exceptions propagate so the engine applies its
        normal isolation (fail THIS request only)."""
        if self._flight is not inner:
            return
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.tokens.append(token)
        if self.on_token is not None:
            self.on_token(token)

    def _remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def _remaining_timeout(self, now: Optional[float] = None) -> Optional[float]:
        if self.timeout is None:
            return None
        now = time.monotonic() if now is None else now
        return self.submitted_at + self.timeout - now

    def _resume_prompt(self) -> np.ndarray:
        """``prompt + tokens_emitted_so_far`` — the failover prompt whose
        re-prefill resumes the stream with zero duplicated tokens."""
        if not self.tokens:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.tokens, np.int32)[None, :]],
            axis=1)

    def _finish(self, status: RequestStatus,
                error: Optional[BaseException] = None):
        with self._lock:
            if self._done.is_set():  # first terminal transition wins
                return
            self.status = status
            self.error = error
            self.finished_at = time.monotonic()
            self._done.set()
            callbacks, self._done_callbacks = self._done_callbacks, []
        for fn in callbacks:  # outside the lock: fn may re-enter this object
            fn(self)

    def __repr__(self):
        return (f"FleetRequest(S={self.prompt_ids.shape[1]}, "
                f"max_new={self.max_new_tokens}, status={self.status.value}, "
                f"tokens={len(self.tokens)}, trail={self.replica_trail})")


class ReplicaSet:
    """N serving-engine replicas behind one submit surface.

    Args:
      engines: the replicas (already constructed — replicas may differ in
        placement but MUST share model, sampling config, and eos id, or
        failover would change the distribution mid-stream).
      failover_block_s: how long a failover resubmission may block waiting
        for queue space on a healthy-but-saturated replica before the
        request is failed outright. The wait runs on the dead engine's
        exiting thread, so it only delays that replica's remaining
        cleanup, never live traffic.
      max_failovers: per-request cap on resubmissions (default: one per
        OTHER replica) — a request that somehow keeps landing on dying
        replicas fails instead of bouncing forever.

    Use as a context manager, or call :meth:`shutdown`.
    """

    def __init__(self, engines: Sequence[ServingEngine], *,
                 failover_block_s: float = 5.0,
                 max_failovers: Optional[int] = None,
                 factories: Optional[Sequence[Optional[Callable]]] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        eos = {e.eos_token_id for e in engines}
        samp = {e._sampling for e in engines}
        if len(eos) > 1 or len(samp) > 1:
            raise ValueError(
                "replicas disagree on sampling config or eos id — failover "
                f"would change the stream's distribution (eos={eos})")
        # Captured fleet-wide config: a parked replica has no engine to
        # read these from, and unpark validates rebuilds against them.
        self._eos = engines[0].eos_token_id
        self._sampling = engines[0]._sampling
        self._replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        #: the SlicePlan behind a from_mesh fleet (None otherwise).
        self.slice_plan = None
        self._failover_block_s = float(failover_block_s)
        self._max_failovers = (len(engines) - 1 if max_failovers is None
                               else int(max_failovers))
        # Per-replica zero-arg engine builders (None = this replica cannot
        # be rebuilt). from_factory/from_mesh fill these in; a supervisor
        # uses them through restart_replica to return FAILED replicas to
        # rotation.
        if factories is None:
            self._factories: list[Optional[Callable]] = [None] * len(engines)
        else:
            self._factories = list(factories)
            if len(self._factories) != len(engines):
                raise ValueError(
                    f"factories must match engines 1:1 "
                    f"(got {len(self._factories)} for {len(engines)})")
        # name -> (adapter, kwargs), in registration order — replayed onto
        # a rebuilt replica's bank so restarts stay tenant-preserving.
        self._adapter_registry: dict = {}
        # Counters folded out of engines that were replaced: merged_stats
        # adds this in so fleet totals stay MONOTONE across restarts.
        self._retired_stats = ServingStats()
        self._lock = threading.Lock()
        self._submitted = 0
        self._failovers = 0      # fence-and-resubmit events (per request)
        self._fences = 0         # replicas demoted to FAILED
        self._failover_failed = 0  # resubmissions that found no home
        self._restarts = 0       # replicas rebuilt back to HEALTHY
        self._hang_fences = 0    # fences on heartbeat stall (watchdog)
        self._crash_loops = 0    # circuit-breaker trips to CRASH_LOOP
        self._scale_ups = 0      # replicas unparked back into rotation
        self._scale_downs = 0    # replicas parked (engine released)
        # Bounded postmortem log: one entry per failover hop, carrying
        # the dead replica's flight-recorder dump (see failover_reports).
        self._failover_reports: list[dict] = []

    @classmethod
    def from_factory(cls, factory: Callable[[], ServingEngine],
                     num_replicas: int, **kwargs) -> "ReplicaSet":
        """Build ``num_replicas`` engines by calling ``factory()`` that
        many times (each call should construct an independent engine —
        sharing params between them is fine and saves host memory). The
        factory is RETAINED per replica, so a :class:`~.supervisor.
        FleetSupervisor` can rebuild a dead replica from it."""
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1 (got {num_replicas})")
        return cls([factory() for _ in range(num_replicas)],
                   factories=[factory] * num_replicas, **kwargs)

    @classmethod
    def from_mesh(cls, model, params=None, *, tp: int,
                  num_slices: Optional[int] = None, devices=None,
                  make_adapters: Optional[Callable] = None,
                  share_prefix_cache: bool = True,
                  failover_block_s: float = 5.0,
                  max_failovers: Optional[int] = None,
                  **engine_kwargs) -> "ReplicaSet":
        """A fleet of tensor-parallel slices: carve the device pool into
        ``num_slices`` disjoint ``tp``-wide slices (every full slice the
        pool affords by default — 8 devices at ``tp=2`` give 4 replicas)
        and build one mesh-sliced :class:`~.engine.ServingEngine` per
        slice. Routing, health, adapter affinity, and token-exact failover
        are exactly the existing machinery — one replica is just a
        multi-chip slice now.

        By default every slice shares ONE host-resident
        :class:`~.scheduler.PrefixCache` (mesh engines cache blocks as
        host numpy, portable across slices), so a prefix prefilled on a
        slice that later dies is still a cache hit when its requests
        resume on a survivor. ``make_adapters`` is a zero-arg factory
        called once per slice — banks hold device state placed on their
        slice's mesh, so they cannot be shared the way params are.

        Remaining ``engine_kwargs`` (``max_slots``, ``max_len``,
        sampling, ...) pass through to every engine.
        """
        from .mesh_exec import SlicePlan
        from .scheduler import PrefixCache

        plan = SlicePlan.plan(tp, num_slices=num_slices, devices=devices)
        cache_mb = engine_kwargs.pop("prefix_cache_mb", 64.0)
        shared_cache = None
        if (share_prefix_cache and cache_mb > 0
                and engine_kwargs.get("prefill_chunk", 256) is not None):
            shared_cache = PrefixCache(int(cache_mb * 2 ** 20))
        def _build_slice(i: int) -> ServingEngine:
            kw = dict(engine_kwargs)
            if make_adapters is not None:
                kw["adapters"] = make_adapters()
            if shared_cache is not None:
                kw["prefix_cache"] = shared_cache
            else:
                kw["prefix_cache_mb"] = cache_mb
            return ServingEngine(model, params,
                                 mesh=plan.build_mesh(i), **kw)

        engines = [_build_slice(i) for i in range(len(plan))]
        # Per-slice rebuild closures: a restarted slice engine gets the
        # SAME mesh, a fresh bank, and the fleet-shared prefix cache — so
        # prefixes its predecessor inserted are warm hits immediately.
        fleet = cls(engines, failover_block_s=failover_block_s,
                    max_failovers=max_failovers,
                    factories=[(lambda i=i: _build_slice(i))
                               for i in range(len(plan))])
        fleet.slice_plan = plan
        return fleet

    # -- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._replicas)

    @property
    def replicas(self) -> list[_Replica]:
        return list(self._replicas)

    def replica_states(self) -> list[ReplicaState]:
        self.refresh_health()
        return [r.state for r in self._replicas]

    @property
    def ready(self) -> bool:
        """At least one replica is healthy and accepting — the gateway's
        ``/readyz`` condition."""
        return bool(self._candidates())

    def engine(self, index: int) -> ServingEngine:
        return self._replicas[index].engine

    # -- health ----------------------------------------------------------
    #: states a fence/kill must leave alone: FAILED is already fenced
    #: (double-fencing would double-count and, via kill, re-inject a fault
    #: into a replacement engine), RESTARTING is mid-rebuild, CRASH_LOOP
    #: is deliberately parked by the breaker, and PARKED holds no engine
    #: at all — only restart_replica, unpark_replica, or reset_circuit
    #: move a replica out of these.
    _FENCED_STATES = (ReplicaState.FAILED, ReplicaState.RESTARTING,
                      ReplicaState.CRASH_LOOP, ReplicaState.PARKED)

    def refresh_health(self):
        """Demote any replica whose engine died since the last look. Lazy —
        called on every routing decision and metrics read, so there is no
        monitor thread to keep alive (or to crash); a
        :class:`~.supervisor.FleetSupervisor` adds the ACTIVE checks
        (heartbeat watchdog, auto-restart) on top."""
        for r in self._replicas:
            if (r.state not in self._FENCED_STATES
                    and r.engine.error is not None):
                self._fence(r)

    def _fence(self, replica: _Replica):
        with self._lock:
            if replica.state in self._FENCED_STATES:
                return
            replica.state = ReplicaState.FAILED
            self._fences += 1

    def drain_replica(self, index: int):
        """Take one replica out of rotation (e.g. before maintenance): no
        new requests route to it, in-flight streams finish normally. Shut
        the engine down once ``engine(i).free_slots == max_slots``."""
        r = self._replicas[index]
        if r.state is ReplicaState.HEALTHY:
            r.state = ReplicaState.DRAINING

    def kill_replica(self, index: int,
                     error: Optional[BaseException] = None):
        """Fault injection / hard fencing: make replica ``index``'s run
        loop raise at its next iteration (see ``ServingEngine.kill``). Its
        in-flight requests fail over to the surviving replicas.
        Idempotent: a replica already fenced (FAILED / RESTARTING /
        CRASH_LOOP) is left alone — its requests were already resubmitted
        once, and a second kill must not re-inject a fault into the
        replacement engine a restart may have installed meanwhile."""
        r = self._replicas[index]
        with self._lock:
            if r.state in self._FENCED_STATES:
                return
        r.engine.kill(error)

    # -- self-healing (used by FleetSupervisor; callable manually) --------
    def restart_replica(self, index: int, *,
                        join_timeout: float = 5.0) -> ServingEngine:
        """Rebuild a FAILED replica from its retained factory and return
        it to HEALTHY rotation: wait for the dead engine's thread (a
        truly wedged one is abandoned — it is a daemon thread whose
        requests were already failed over), build + warm a replacement
        (the factory runs the normal three-executable warmup), replay
        every fleet adapter registration onto its bank, fold the dead
        engine's counters into the retired-stats ledger (fleet totals
        stay monotone), and only THEN swap it in. Raises ``RuntimeError``
        when the replica has no factory or is not FAILED, and propagates
        factory/warmup errors — the caller (supervisor) counts those as
        failed attempts toward the circuit breaker."""
        r = self._replicas[index]
        factory = self._factories[index]
        if factory is None:
            raise RuntimeError(
                f"replica {index} has no factory (build the fleet with "
                "from_factory/from_mesh, or pass factories= to ReplicaSet)")
        with self._lock:
            if r.state is not ReplicaState.FAILED:
                raise RuntimeError(
                    f"replica {index} is {r.state.value}, not failed — "
                    "only a fenced replica can be restarted")
            r.state = ReplicaState.RESTARTING
        old = r.engine
        try:
            # The old engine's thread must be DONE retiring its requests
            # before the swap: _on_inner_finish closures read
            # ``replica.engine.error`` to classify a failure as
            # engine-death, and swapping early would make a late retire
            # read the replacement's None error and skip failover.
            thread = old._thread
            if thread is not None and thread.is_alive():
                old._stop = True
                thread.join(join_timeout)
            try:
                old.shutdown(drain=False, timeout=1.0)
            except Exception:
                pass  # a dead engine re-raises its own fatal error here
            new_engine = factory()
            new_engine.start()  # no-op unless the factory used autostart=False
            if not new_engine.healthy:
                raise RuntimeError(
                    "replacement engine came up unhealthy"
                ) from new_engine.error
            if (new_engine.eos_token_id != self.eos_token_id
                    or new_engine._sampling != old._sampling):
                raise ValueError(
                    "factory built an engine whose eos/sampling config "
                    "disagrees with the fleet — failover would change the "
                    "stream's distribution")
            with self._lock:
                registry = list(self._adapter_registry.items())
            for name, (adapter, kwargs) in registry:
                new_engine.register_adapter(name, adapter, **kwargs)
        except BaseException:
            with self._lock:
                r.state = ReplicaState.FAILED
            raise
        with self._lock:
            self._retired_stats.merge(old.stats)
            r.engine = new_engine
            r.state = ReplicaState.HEALTHY
            r.restarts += 1
            self._restarts += 1
        return new_engine

    def trip_breaker(self, index: int):
        """Park a FAILED replica in CRASH_LOOP: it leaves the restart
        rotation entirely (no further rebuild attempts, excluded from
        routing, kill_replica no-ops) until :meth:`reset_circuit`. The
        supervisor calls this when restarts exceed its window budget."""
        r = self._replicas[index]
        with self._lock:
            if r.state is ReplicaState.CRASH_LOOP:
                return
            r.state = ReplicaState.CRASH_LOOP
            self._crash_loops += 1

    def reset_circuit(self, index: int):
        """Operator override: move a CRASH_LOOP replica back to FAILED so
        the supervisor may try restarting it again (e.g. after the
        poisoned host was actually fixed)."""
        r = self._replicas[index]
        with self._lock:
            if r.state is ReplicaState.CRASH_LOOP:
                r.state = ReplicaState.FAILED

    def _note_hang_fence(self):
        with self._lock:
            self._hang_fences += 1

    # -- autoscaling (used by control.FleetAutoscaler; callable manually) --
    def park_replica(self, index: int):
        """Scale-down terminal step: release an IDLE replica's engine
        entirely (decode state, KV pool, compiled executables all freed)
        while keeping its slot and factory, so :meth:`unpark_replica` can
        bring it back later. Only an idle HEALTHY or DRAINING replica may
        be parked — parking live streams would drop tokens, so the
        autoscaler drains first and parks once ``free_slots == max_slots``
        and the queue is empty. The engine's counters fold into the
        retired-stats ledger (fleet totals stay monotone). Raises
        ``RuntimeError`` when the replica has no factory, is not
        HEALTHY/DRAINING, or still holds work."""
        r = self._replicas[index]
        if self._factories[index] is None:
            raise RuntimeError(
                f"replica {index} has no factory — a parked replica could "
                "never be rebuilt (build the fleet with from_factory/"
                "from_mesh, or pass factories= to ReplicaSet)")
        with self._lock:
            if r.state not in (ReplicaState.HEALTHY, ReplicaState.DRAINING):
                raise RuntimeError(
                    f"replica {index} is {r.state.value} — only a healthy "
                    "or draining replica can be parked")
            engine = r.engine
            if (engine.free_slots != engine.max_slots
                    or engine.queue_depth > 0):
                raise RuntimeError(
                    f"replica {index} still holds work "
                    f"({engine.max_slots - engine.free_slots} active, "
                    f"{engine.queue_depth} queued) — drain it first")
            r.state = ReplicaState.PARKED
        try:
            engine.shutdown(drain=False, timeout=1.0)
        except Exception:
            pass  # an already-dead engine re-raises its own error here
        with self._lock:
            self._retired_stats.merge(engine.stats)
            r.engine = None
            self._scale_downs += 1

    def unpark_replica(self, index: int) -> ServingEngine:
        """Scale-up: rebuild a PARKED replica from its retained factory
        and return it to HEALTHY rotation — :meth:`restart_replica`'s
        twin minus the dead-engine teardown (there is no engine to tear
        down). The rebuild is validated against the CAPTURED fleet
        eos/sampling config and replays every fleet adapter registration,
        so scale-up is tenant-preserving. Propagates factory/warmup
        errors with the replica returned to PARKED — the autoscaler
        counts those and backs off."""
        r = self._replicas[index]
        factory = self._factories[index]
        if factory is None:
            raise RuntimeError(f"replica {index} has no factory")
        with self._lock:
            if r.state is not ReplicaState.PARKED:
                raise RuntimeError(
                    f"replica {index} is {r.state.value}, not parked — "
                    "only a parked replica can be unparked")
            r.state = ReplicaState.RESTARTING
        try:
            new_engine = factory()
            new_engine.start()
            if not new_engine.healthy:
                raise RuntimeError(
                    "replacement engine came up unhealthy"
                ) from new_engine.error
            if (new_engine.eos_token_id != self._eos
                    or new_engine._sampling != self._sampling):
                raise ValueError(
                    "factory built an engine whose eos/sampling config "
                    "disagrees with the fleet — failover would change the "
                    "stream's distribution")
            with self._lock:
                registry = list(self._adapter_registry.items())
            for name, (adapter, kwargs) in registry:
                new_engine.register_adapter(name, adapter, **kwargs)
        except BaseException:
            with self._lock:
                r.state = ReplicaState.PARKED
            raise
        with self._lock:
            r.engine = new_engine
            r.state = ReplicaState.HEALTHY
            r.restarts += 1
            self._scale_ups += 1
        return new_engine

    def add_parked(self, factory: Callable[[], ServingEngine]) -> int:
        """Append a PARKED engine-less replica slot holding only
        ``factory`` — headroom the autoscaler can later spawn into
        without the fleet ever paying for an engine it hasn't needed yet.
        Returns the new replica's index."""
        with self._lock:
            index = len(self._replicas)
            r = _Replica(index, None)
            r.state = ReplicaState.PARKED
            self._replicas.append(r)
            self._factories.append(factory)
        return index

    # -- projected pressure (gateway shed inputs) -------------------------
    def projected_page_deficit(self, total_tokens: int) -> int:
        """Fleet-level projected page shortfall for a ``total_tokens``
        request: the MINIMUM over healthy replicas of
        :meth:`~.engine.ServingEngine.projected_page_deficit` — one
        replica with headroom means the request has a home, so only when
        EVERY healthy replica is short does the gateway shed. 0 when any
        replica is dense or has room (and when none is healthy — the
        no-replica path 503s instead)."""
        deficits = [r.engine.projected_page_deficit(total_tokens)
                    for r in self._replicas
                    if r.state is ReplicaState.HEALTHY and r.engine.healthy]
        return min(deficits) if deficits else 0

    def page_drain_rate(self) -> float:
        """Observed pages/s freed across the healthy fleet (sum over
        replicas) — the denominator of the shed path's Retry-After."""
        return sum(r.engine.page_drain_rate() for r in self._replicas
                   if r.state is ReplicaState.HEALTHY and r.engine.healthy)

    def admission_capacity(self) -> int:
        """Total streams the healthy fleet can hold at once — decode
        slots plus admission-queue depth, summed over healthy replicas.
        The denominator of the gateway's fair-share occupancy check."""
        return sum(r.engine.max_slots + r.engine._queue.max_queued
                   for r in self._replicas
                   if r.state is ReplicaState.HEALTHY and r.engine.healthy)

    @property
    def eos_token_id(self):
        """The fleet-shared eos id (validated identical across replicas;
        captured at construction so it survives replica 0 being parked)."""
        return self._eos

    # -- routing ---------------------------------------------------------
    def _candidates(self, adapter: Optional[str] = None,
                    total_tokens: int = 0,
                    prompt_ids=None) -> list[_Replica]:
        """Healthy replicas, best-first: replicas with a free slot before
        saturated ones, then longest cached prefix for THIS prompt, then
        most free decode slots, then lowest total occupancy, then KV-page
        headroom, then index (stable). ``total_tokens`` (prompt + max_new)
        folds the paged pool into the score: a replica whose pool is
        short pages for THIS request (``engine.page_deficit``) loses the
        tie-break to one with room, and among un-starved replicas more
        ``free_pages`` wins — so long prompts route to replicas with free
        pages instead of forcing preemption (``fleet_free_pages`` is the
        same signal summed fleet-wide in :meth:`fleet_metrics`).
        ``prompt_ids`` enables prefix-cache-aware placement: each
        replica's :meth:`~.engine.ServingEngine.cached_prefix_tokens`
        probe (pure host hashing, no LRU promotion) scores how much
        prefill the replica can skip, so shared-system-prompt traffic
        lands where its KV already lives — but never at the cost of
        queueing behind a saturated replica while another has a free slot
        (the leading ``no-free-slot`` term). When the request names a
        LoRA adapter, replicas with that adapter already RESIDENT in
        their device bank rank first (routing affinity saves a host→
        device row upload), engines built without a bank drop out
        entirely, and the same order breaks ties."""
        self.refresh_health()
        cands = [r for r in self._replicas
                 if r.state is ReplicaState.HEALTHY and r.engine.healthy
                 and (adapter is None or r.engine.adapters is not None)]

        def _cached(r):
            if prompt_ids is None:
                return 0
            return r.engine.cached_prefix_tokens(prompt_ids, adapter)

        def _pages_key(r):
            return (r.engine.page_deficit(total_tokens), -r.engine.free_pages)

        if adapter is None:
            cands.sort(key=lambda r: (r.engine.free_slots == 0, -_cached(r),
                                      -r.engine.free_slots, r.engine.load,
                                      *_pages_key(r), r.index))
        else:
            cands.sort(key=lambda r: (not r.engine.adapter_resident(adapter),
                                      r.engine.free_slots == 0, -_cached(r),
                                      -r.engine.free_slots, r.engine.load,
                                      *_pages_key(r), r.index))
        return cands

    def submit(self, prompt_ids=None, *, max_new_tokens: int = 20,
               seed: Optional[int] = None, rng=None,
               timeout: Optional[float] = None, on_token=None,
               ignore_eos: bool = False, adapter: Optional[str] = None,
               trace_id: Optional[str] = None,
               priority: Optional[str] = None,
               block: bool = False,
               block_timeout: Optional[float] = None) -> FleetRequest:
        """Route one request to the least-loaded healthy replica; returns
        a :class:`FleetRequest` immediately. Raises
        :class:`~.scheduler.QueueFull` when every healthy replica's
        admission queue is full (``block=True`` waits for space on the
        best one first, up to ``block_timeout``), ``RuntimeError`` when no
        replica is healthy at all, and ``LookupError``
        (:class:`~..adapters.registry.UnknownAdapterError`) when
        ``adapter`` names an adapter no healthy replica has registered —
        the signal the gateway maps to HTTP 404."""
        fleet = FleetRequest(prompt_ids, max_new_tokens=max_new_tokens,
                             rng=rng, seed=seed, timeout=timeout,
                             on_token=on_token, ignore_eos=ignore_eos,
                             adapter=adapter, trace_id=trace_id,
                             priority=priority)
        fleet.submitted_at = time.monotonic()
        with self._lock:
            self._submitted += 1
        self._dispatch(fleet, block=block, block_timeout=block_timeout)
        return fleet

    def _dispatch(self, fleet: FleetRequest, *, block: bool,
                  block_timeout: Optional[float], _raise: bool = True):
        """Try candidates best-first with non-blocking submits; only after
        ALL are queue-full does ``block=True`` wait on the current best.
        With ``_raise=False`` (failover path, running on a dead engine's
        thread) failures finish the fleet request instead of raising."""
        last_exc: Optional[BaseException] = None
        saturated = False
        # Page-aware score input: tokens this request will occupy (prompt +
        # already-generated on failover resume + remaining decode budget).
        total_tokens = (int(fleet.prompt_ids.shape[1]) + len(fleet.tokens)
                        + int(fleet.max_new_tokens))
        # Cache-aware score input: the prompt that will actually prefill
        # (the RESUME prompt on failover — its longer prefix is exactly
        # what the dead replica's shared-cache inserts make warm).
        probe_ids = fleet._resume_prompt()
        for attempt in range(2):
            for r in self._candidates(fleet.adapter, total_tokens=total_tokens,
                                      prompt_ids=probe_ids):
                inner = self._make_inner(fleet, r)
                if inner is None:  # cancelled or deadline passed meanwhile
                    return
                try:
                    r.engine.submit(
                        request=inner,
                        block=block and attempt > 0,
                        block_timeout=block_timeout)
                except QueueFull as e:
                    last_exc, saturated = e, True
                    continue
                except LookupError as e:
                    # THIS replica's registry doesn't know the adapter
                    # (registries may trail during a rollout) — try the
                    # next one; when nobody knows, the LookupError
                    # surfaces to the caller as-is (gateway → 404).
                    last_exc = e
                    continue
                except RuntimeError as e:
                    # Died between the health check and the enqueue.
                    last_exc = e
                    self._fence(r)
                    continue
                with fleet._lock:
                    fleet._inner = inner
                fleet.replica_trail.append(r.index)
                if fleet.cancel_requested:
                    inner.cancel()  # cancel raced the dispatch
                return
            if not (block and saturated):
                break
        if _raise:
            if saturated:
                raise QueueFull(
                    "every healthy replica's admission queue is full; "
                    "retry later") from last_exc
            if isinstance(last_exc, LookupError):
                raise last_exc
            raise RuntimeError(
                "no healthy replica available") from last_exc
        with self._lock:
            self._failover_failed += 1
        fleet._finish(RequestStatus.FAILED, RuntimeError(
            "failover found no healthy replica with queue space")
            if last_exc is None else last_exc)

    def _make_inner(self, fleet: FleetRequest,
                    replica: _Replica) -> Optional[Request]:
        """Build the next flight: the remaining-budget request whose prompt
        is ``original + emitted`` (so token budgets, deadline, and KV
        occupancy all add up to exactly the uninterrupted request's)."""
        if fleet.cancel_requested:
            fleet._finish(RequestStatus.CANCELLED)
            return None
        remaining_t = fleet._remaining_timeout()
        if remaining_t is not None and remaining_t <= 0:
            fleet._finish(RequestStatus.TIMED_OUT)
            return None
        inner = Request(fleet._resume_prompt(),
                        max_new_tokens=fleet._remaining_new_tokens(),
                        rng=fleet.rng, seed=fleet.seed,
                        timeout=remaining_t, on_token=None,
                        ignore_eos=fleet.ignore_eos,
                        adapter=fleet.adapter,
                        trace_id=fleet.trace_id,
                        priority=fleet.priority)
        inner.on_token = lambda tok, _inner=inner: fleet._emit_from(
            _inner, tok)
        inner._on_finish = lambda req: self._on_inner_finish(
            fleet, replica, req)
        # Mark this as the live flight BEFORE submission: the engine may
        # emit tokens before _dispatch gets around to recording _inner.
        # Dispatch builds inners strictly one at a time (a candidate that
        # rejected the submit never emitted), so latest-built == live.
        with fleet._lock:
            fleet._flight = inner
        return inner

    # -- adapters ---------------------------------------------------------
    def register_adapter(self, name: str, adapter, **kwargs):
        """Register a LoRA adapter on EVERY replica's bank. Fleet-wide
        registration is what makes failover tenant-preserving: a stream
        decoding under adapter X can resume on any survivor, which loads
        X into its own bank at admission if it isn't already resident.
        Raises ``RuntimeError`` if any replica was built without an
        :class:`~..adapters.registry.AdapterBank`. Registrations are
        RECORDED: a replica rebuilt by :meth:`restart_replica` replays
        them onto its fresh bank, so restarts are tenant-preserving —
        and a PARKED replica (no engine) picks them up at unpark."""
        for r in self._replicas:
            if r.engine is not None:
                r.engine.register_adapter(name, adapter, **kwargs)
        with self._lock:
            self._adapter_registry[name] = (adapter, dict(kwargs))

    def unregister_adapter(self, name: str):
        """Drop a named adapter from every replica that knows it (idle
        banks only free the device row lazily on the next eviction)."""
        with self._lock:
            self._adapter_registry.pop(name, None)
        for r in self._replicas:
            bank = r.engine.adapters if r.engine is not None else None
            if bank is not None and name in bank.names():
                bank.unregister(name)

    # -- failover ---------------------------------------------------------
    def _on_inner_finish(self, fleet: FleetRequest, replica: _Replica,
                         inner: Request):
        """Runs ON THE ENGINE THREAD at the inner request's terminal
        transition. Engine-death failures fence the replica and resubmit;
        everything else (completion, cancellation, deadline, a raising
        user callback) passes through to the fleet handle."""
        if inner.status is RequestStatus.FAILED \
                and replica.engine.error is not None \
                and not fleet.cancel_requested:
            self._fence(replica)
            # Attach the dead replica's postmortem (its engine froze the
            # flight-recorder dump — fatal event included — before this
            # retire sweep started) so the hop is debuggable after the
            # fact without the replica.
            report = {
                "trace_id": fleet.trace_id,
                "replica": replica.index,
                "error": repr(replica.engine.error),
                "tokens_at_failover": len(fleet.tokens),
                "flight_recorder": replica.engine.postmortem(),
            }
            with self._lock:
                self._failover_reports.append(report)
                del self._failover_reports[:-32]  # keep the last 32 hops
            if fleet.failovers >= self._max_failovers:
                fleet._finish(RequestStatus.FAILED, RuntimeError(
                    f"request failed over {fleet.failovers} times "
                    "(max_failovers reached)"))
                return
            with self._lock:
                self._failovers += 1
                replica.failures += 1
            self._dispatch(fleet, block=True,
                           block_timeout=self._failover_block_s,
                           _raise=False)
            return
        fleet._finish(inner.status, inner.error)

    @property
    def failover_reports(self) -> list[dict]:
        """Postmortems for the most recent failover hops (newest last):
        ``{trace_id, replica, error, tokens_at_failover, flight_recorder}``
        where ``flight_recorder`` is the dead engine's frozen event dump
        (fatal event included). Bounded to the last 32 hops."""
        with self._lock:
            return list(self._failover_reports)

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        """One fleet-wide Chrome-trace dict: every replica's buffered
        spans (optionally filtered to one ``trace_id``) merged onto the
        shared monotonic timeline — a failed-over request shows its
        replica-A spans next to its replica-B continuation. Backs the
        gateway's ``GET /debug/trace``."""
        from ..observability import merge_chrome_traces

        return merge_chrome_traces(
            r.engine.chrome_trace(trace_id) for r in self._replicas
            if r.engine is not None)

    # -- metrics ----------------------------------------------------------
    def merged_stats(self) -> ServingStats:
        """A fresh :class:`ServingStats` holding the fleet-wide fold of
        every replica's counters (see ``ServingStats.merge``), INCLUDING
        the retired-stats ledger of engines replaced by
        :meth:`restart_replica` — fleet totals are monotone across
        restarts, not reset by them."""
        merged = ServingStats()
        with self._lock:
            merged.merge(self._retired_stats)
        for r in self._replicas:
            if r.engine is not None:
                merged.merge(r.engine.stats)
        return merged

    def fleet_metrics(self) -> dict:
        """Merged engine summary plus router-level counters (replica
        states, failover/fence counts) — the dict behind ``/metrics``."""
        self.refresh_health()
        out = self.merged_stats().summary()
        states = [r.state for r in self._replicas]
        with self._lock:
            out.update({
                "replicas": len(self._replicas),
                "replicas_healthy": sum(
                    s is ReplicaState.HEALTHY for s in states),
                "replicas_draining": sum(
                    s is ReplicaState.DRAINING for s in states),
                "replicas_failed": sum(
                    s is ReplicaState.FAILED for s in states),
                "replicas_restarting": sum(
                    s is ReplicaState.RESTARTING for s in states),
                "replicas_crash_loop": sum(
                    s is ReplicaState.CRASH_LOOP for s in states),
                "replicas_parked": sum(
                    s is ReplicaState.PARKED for s in states),
                "fleet_submitted": self._submitted,
                "fleet_failovers": self._failovers,
                "fleet_fences": self._fences,
                "fleet_failover_failed": self._failover_failed,
                "fleet_restarts": self._restarts,
                "fleet_hang_fences": self._hang_fences,
                "fleet_crash_loops": self._crash_loops,
                "fleet_scale_ups": self._scale_ups,
                "fleet_scale_downs": self._scale_downs,
                # One autoscale actuation = one unpark or one park; the
                # loop-closure gauge the SLO acceptance reads.
                "fleet_autoscale_events": self._scale_ups + self._scale_downs,
                "fleet_free_slots": sum(
                    r.engine.free_slots for r in self._replicas
                    if r.state is ReplicaState.HEALTHY and r.engine.healthy),
                # Paged-KV headroom across the healthy fleet (0 when every
                # replica is dense). Page pressure already steers routing
                # through ``engine.load``; this is the operator's view.
                "fleet_free_pages": sum(
                    r.engine.free_pages for r in self._replicas
                    if r.state is ReplicaState.HEALTHY and r.engine.healthy),
                # Observed pages/s returning to the healthy fleet's pools
                # — the drain rate behind shed Retry-After values.
                "fleet_page_drain_rate": round(sum(
                    r.engine.page_drain_rate() for r in self._replicas
                    if r.state is ReplicaState.HEALTHY
                    and r.engine.healthy), 4),
            })
        return out

    # -- lifecycle --------------------------------------------------------
    def drain(self):
        """Stop routing new work everywhere (all HEALTHY → DRAINING);
        in-flight streams keep running. The gateway's SIGTERM path."""
        for r in self._replicas:
            if r.state is ReplicaState.HEALTHY:
                r.state = ReplicaState.DRAINING

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Shut every replica down (``drain=True`` finishes accepted work
        first). Replicas that already died are fenced, not re-raised —
        their error was already delivered to their requests."""
        first_exc: Optional[BaseException] = None
        for r in self._replicas:
            if r.engine is None:  # parked: nothing to shut down
                continue
            try:
                r.engine.shutdown(drain=drain, timeout=timeout)
            except RuntimeError as e:
                self._fence(r)
                if r.engine.error is None and first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
