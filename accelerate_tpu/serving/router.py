"""Multi-replica routing and fault-tolerant failover over serving engines.

One :class:`~.engine.ServingEngine` is both a capacity ceiling and a
single point of failure: its fixed ``[max_slots, max_len]`` decode state
bounds concurrency, and its single engine thread dying fails every
in-flight stream. The :class:`ReplicaSet` is the serving-side analogue of
data-parallel sharding over the device mesh — N independently compiled,
independently failing engine replicas behind one submit surface:

* **Routing** — least-loaded: a new request goes to the healthy replica
  with the most free decode slots (ties broken by total occupancy
  ``engine.load``, then index). When the best replica's admission queue
  is full the next one is tried; only when EVERY healthy replica is
  saturated does the router surface :class:`~.scheduler.QueueFull` — the
  signal the gateway maps to HTTP 429.
* **Health** — per-replica :class:`ReplicaState`:
  HEALTHY (in rotation) → DRAINING (out of rotation, finishing its
  streams — operator-initiated via :meth:`ReplicaSet.drain_replica`) →
  FAILED (fenced). Health is refreshed lazily on every routing decision
  and metrics read — an engine whose run loop recorded a fatal error is
  demoted without any monitor thread.
* **Failover** — a replica whose run loop raises fails every request it
  held (the engine's own cleanup path). The router hooks each request's
  terminal transition: when the cause of death was the ENGINE (not the
  request), the replica is fenced and the request is resubmitted to a
  healthy replica as ``prompt + tokens_emitted_so_far``, so the stream
  RESUMES — no token is re-emitted, none is lost. Re-prefilling the
  grown prompt is exactly the work the chunk-aligned prefix cache makes
  cheap. For greedy decoding the resumed stream is token-identical to an
  uninterrupted one (prefill's first-token selection at position
  ``len - 1`` is the same computation as the decode step there); sampled
  streams resume without duplicates or gaps but restart the rng chain at
  the failover point, so the continuation is a fresh draw.

The caller-facing handle is a :class:`FleetRequest`: it survives
failovers (accumulating tokens across however many inner
:class:`~.request.Request` flights it takes) while mirroring the Request
API — ``tokens``, ``wait``, ``result``, ``output_ids``, ``cancel``.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from ..observability import new_trace_id
from .engine import ServingEngine
from .metrics import ServingStats
from .request import Request, RequestStatus
from .scheduler import QueueFull

__all__ = ["ReplicaSet", "ReplicaState", "FleetRequest"]


class ReplicaState(enum.Enum):
    HEALTHY = "healthy"     # in rotation, taking new requests
    DRAINING = "draining"   # out of rotation, finishing in-flight streams
    FAILED = "failed"       # fenced: run loop died or operator killed it


class _Replica:
    """One engine plus its routing state (router internals)."""

    def __init__(self, index: int, engine: ServingEngine):
        self.index = index
        self.engine = engine
        self.state = ReplicaState.HEALTHY
        self.failures = 0  # requests this replica failed over FROM

    def __repr__(self):
        return (f"_Replica({self.index}, {self.state.value}, "
                f"free={self.engine.free_slots})")


class FleetRequest:
    """Router-level handle for one generation, stable across failovers.

    Tokens stream into :attr:`tokens` (and through ``on_token``) exactly
    once each, no matter how many replicas the request visits; the
    per-flight inner :class:`~.request.Request` objects are an
    implementation detail. The per-request deadline is GLOBAL — time
    spent on a replica that later died still counts against ``timeout``.
    """

    def __init__(self, prompt_ids, max_new_tokens: int = 20,
                 rng=None, seed: Optional[int] = None,
                 timeout: Optional[float] = None,
                 on_token: Optional[Callable[[int], None]] = None,
                 ignore_eos: bool = False,
                 adapter: Optional[str] = None,
                 trace_id: Optional[str] = None):
        # Reuse Request's prompt validation (shape + max_new bounds +
        # adapter/trace id form).
        proto = Request(prompt_ids, max_new_tokens=max_new_tokens,
                        adapter=adapter, trace_id=trace_id)
        self.prompt_ids = proto.prompt_ids
        self.max_new_tokens = proto.max_new_tokens
        self.rng = rng
        self.seed = seed
        self.timeout = timeout
        self.on_token = on_token
        self.ignore_eos = ignore_eos
        #: named LoRA adapter, preserved across failovers (None = base).
        self.adapter = proto.adapter
        #: correlation id shared by every flight this request takes —
        #: minted here (when the gateway didn't) so the spans a failover
        #: leaves on replica A and the resumed spans on replica B carry
        #: the SAME id and merge into one timeline.
        self.trace_id = proto.trace_id or new_trace_id()

        self.tokens: list[int] = []
        self.status = RequestStatus.QUEUED
        self.error: Optional[BaseException] = None
        #: replica indices this request ran on, in order (one entry when no
        #: failover happened; the failover test asserts on its length).
        self.replica_trail: list[int] = []

        self.submitted_at: Optional[float] = None
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None

        self._cancel_requested = False
        self._done = threading.Event()
        self._lock = threading.Lock()
        self._inner: Optional[Request] = None

    # -- caller API (mirrors Request) -----------------------------------
    def cancel(self):
        """Cancel the current flight; honored at the owning engine's next
        scheduler pass, and suppresses any further failover."""
        self._cancel_requested = True
        with self._lock:
            inner = self._inner
        if inner is not None:
            inner.cancel()

    @property
    def cancel_requested(self) -> bool:
        return self._cancel_requested

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def failovers(self) -> int:
        """How many times this request was resubmitted after a replica
        died (0 for an uninterrupted stream)."""
        return max(0, len(self.replica_trail) - 1)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Generated token ids [n] (prompt excluded), blocking until done;
        same error contract as :meth:`Request.result`."""
        if not self._done.wait(timeout):
            raise TimeoutError("request still in flight")
        if self.status != RequestStatus.COMPLETED:
            raise RuntimeError(
                f"request {self.status.value}"
                + (f": {self.error}" if self.error is not None else "")
            ) from self.error
        return np.asarray(self.tokens, np.int32)

    def output_ids(self, timeout: Optional[float] = None) -> np.ndarray:
        """[1, S + n] prompt + completion — the offline ``generate`` shape."""
        toks = self.result(timeout)
        return np.concatenate([self.prompt_ids, toks[None, :]], axis=1)

    # -- router internals ------------------------------------------------
    def _emit(self, token: int):
        """Inner on_token trampoline: runs on whichever engine thread owns
        the current flight. Exceptions propagate so the engine applies its
        normal callback-failure isolation (fail THIS request only)."""
        if self.first_token_at is None:
            self.first_token_at = time.monotonic()
        self.tokens.append(token)
        if self.on_token is not None:
            self.on_token(token)

    def _remaining_new_tokens(self) -> int:
        return self.max_new_tokens - len(self.tokens)

    def _remaining_timeout(self, now: Optional[float] = None) -> Optional[float]:
        if self.timeout is None:
            return None
        now = time.monotonic() if now is None else now
        return self.submitted_at + self.timeout - now

    def _resume_prompt(self) -> np.ndarray:
        """``prompt + tokens_emitted_so_far`` — the failover prompt whose
        re-prefill resumes the stream with zero duplicated tokens."""
        if not self.tokens:
            return self.prompt_ids
        return np.concatenate(
            [self.prompt_ids, np.asarray(self.tokens, np.int32)[None, :]],
            axis=1)

    def _finish(self, status: RequestStatus,
                error: Optional[BaseException] = None):
        with self._lock:
            if self._done.is_set():  # first terminal transition wins
                return
            self.status = status
            self.error = error
            self.finished_at = time.monotonic()
            self._done.set()

    def __repr__(self):
        return (f"FleetRequest(S={self.prompt_ids.shape[1]}, "
                f"max_new={self.max_new_tokens}, status={self.status.value}, "
                f"tokens={len(self.tokens)}, trail={self.replica_trail})")


class ReplicaSet:
    """N serving-engine replicas behind one submit surface.

    Args:
      engines: the replicas (already constructed — replicas may differ in
        placement but MUST share model, sampling config, and eos id, or
        failover would change the distribution mid-stream).
      failover_block_s: how long a failover resubmission may block waiting
        for queue space on a healthy-but-saturated replica before the
        request is failed outright. The wait runs on the dead engine's
        exiting thread, so it only delays that replica's remaining
        cleanup, never live traffic.
      max_failovers: per-request cap on resubmissions (default: one per
        OTHER replica) — a request that somehow keeps landing on dying
        replicas fails instead of bouncing forever.

    Use as a context manager, or call :meth:`shutdown`.
    """

    def __init__(self, engines: Sequence[ServingEngine], *,
                 failover_block_s: float = 5.0,
                 max_failovers: Optional[int] = None):
        engines = list(engines)
        if not engines:
            raise ValueError("ReplicaSet needs at least one engine")
        eos = {e.eos_token_id for e in engines}
        samp = {e._sampling for e in engines}
        if len(eos) > 1 or len(samp) > 1:
            raise ValueError(
                "replicas disagree on sampling config or eos id — failover "
                f"would change the stream's distribution (eos={eos})")
        self._replicas = [_Replica(i, e) for i, e in enumerate(engines)]
        #: the SlicePlan behind a from_mesh fleet (None otherwise).
        self.slice_plan = None
        self._failover_block_s = float(failover_block_s)
        self._max_failovers = (len(engines) - 1 if max_failovers is None
                               else int(max_failovers))
        self._lock = threading.Lock()
        self._submitted = 0
        self._failovers = 0      # fence-and-resubmit events (per request)
        self._fences = 0         # replicas demoted to FAILED
        self._failover_failed = 0  # resubmissions that found no home
        # Bounded postmortem log: one entry per failover hop, carrying
        # the dead replica's flight-recorder dump (see failover_reports).
        self._failover_reports: list[dict] = []

    @classmethod
    def from_factory(cls, factory: Callable[[], ServingEngine],
                     num_replicas: int, **kwargs) -> "ReplicaSet":
        """Build ``num_replicas`` engines by calling ``factory()`` that
        many times (each call should construct an independent engine —
        sharing params between them is fine and saves host memory)."""
        if num_replicas < 1:
            raise ValueError(f"num_replicas must be >= 1 (got {num_replicas})")
        return cls([factory() for _ in range(num_replicas)], **kwargs)

    @classmethod
    def from_mesh(cls, model, params=None, *, tp: int,
                  num_slices: Optional[int] = None, devices=None,
                  make_adapters: Optional[Callable] = None,
                  share_prefix_cache: bool = True,
                  failover_block_s: float = 5.0,
                  max_failovers: Optional[int] = None,
                  **engine_kwargs) -> "ReplicaSet":
        """A fleet of tensor-parallel slices: carve the device pool into
        ``num_slices`` disjoint ``tp``-wide slices (every full slice the
        pool affords by default — 8 devices at ``tp=2`` give 4 replicas)
        and build one mesh-sliced :class:`~.engine.ServingEngine` per
        slice. Routing, health, adapter affinity, and token-exact failover
        are exactly the existing machinery — one replica is just a
        multi-chip slice now.

        By default every slice shares ONE host-resident
        :class:`~.scheduler.PrefixCache` (mesh engines cache blocks as
        host numpy, portable across slices), so a prefix prefilled on a
        slice that later dies is still a cache hit when its requests
        resume on a survivor. ``make_adapters`` is a zero-arg factory
        called once per slice — banks hold device state placed on their
        slice's mesh, so they cannot be shared the way params are.

        Remaining ``engine_kwargs`` (``max_slots``, ``max_len``,
        sampling, ...) pass through to every engine.
        """
        from .mesh_exec import SlicePlan
        from .scheduler import PrefixCache

        plan = SlicePlan.plan(tp, num_slices=num_slices, devices=devices)
        cache_mb = engine_kwargs.pop("prefix_cache_mb", 64.0)
        shared_cache = None
        if (share_prefix_cache and cache_mb > 0
                and engine_kwargs.get("prefill_chunk", 256) is not None):
            shared_cache = PrefixCache(int(cache_mb * 2 ** 20))
        engines = []
        for i in range(len(plan)):
            kw = dict(engine_kwargs)
            if make_adapters is not None:
                kw["adapters"] = make_adapters()
            if shared_cache is not None:
                kw["prefix_cache"] = shared_cache
            else:
                kw["prefix_cache_mb"] = cache_mb
            engines.append(ServingEngine(model, params,
                                         mesh=plan.build_mesh(i), **kw))
        fleet = cls(engines, failover_block_s=failover_block_s,
                    max_failovers=max_failovers)
        fleet.slice_plan = plan
        return fleet

    # -- introspection ---------------------------------------------------
    def __len__(self) -> int:
        return len(self._replicas)

    @property
    def replicas(self) -> list[_Replica]:
        return list(self._replicas)

    def replica_states(self) -> list[ReplicaState]:
        self.refresh_health()
        return [r.state for r in self._replicas]

    @property
    def ready(self) -> bool:
        """At least one replica is healthy and accepting — the gateway's
        ``/readyz`` condition."""
        return bool(self._candidates())

    def engine(self, index: int) -> ServingEngine:
        return self._replicas[index].engine

    # -- health ----------------------------------------------------------
    def refresh_health(self):
        """Demote any replica whose engine died since the last look. Lazy —
        called on every routing decision and metrics read, so there is no
        monitor thread to keep alive (or to crash)."""
        for r in self._replicas:
            if r.state is not ReplicaState.FAILED and r.engine.error is not None:
                self._fence(r)

    def _fence(self, replica: _Replica):
        with self._lock:
            if replica.state is ReplicaState.FAILED:
                return
            replica.state = ReplicaState.FAILED
            self._fences += 1

    def drain_replica(self, index: int):
        """Take one replica out of rotation (e.g. before maintenance): no
        new requests route to it, in-flight streams finish normally. Shut
        the engine down once ``engine(i).free_slots == max_slots``."""
        r = self._replicas[index]
        if r.state is ReplicaState.HEALTHY:
            r.state = ReplicaState.DRAINING

    def kill_replica(self, index: int,
                     error: Optional[BaseException] = None):
        """Fault injection / hard fencing: make replica ``index``'s run
        loop raise at its next iteration (see ``ServingEngine.kill``). Its
        in-flight requests fail over to the surviving replicas."""
        self._replicas[index].engine.kill(error)

    # -- routing ---------------------------------------------------------
    def _candidates(self, adapter: Optional[str] = None,
                    total_tokens: int = 0) -> list[_Replica]:
        """Healthy replicas, best-first: most free decode slots, then
        lowest total occupancy, then KV-page headroom, then index
        (stable). ``total_tokens`` (prompt + max_new) folds the paged
        pool into the score: a replica whose pool is short pages for THIS
        request (``engine.page_deficit``) loses the tie-break to one with
        room, and among un-starved replicas more ``free_pages`` wins — so
        long prompts route to replicas with free pages instead of forcing
        preemption (``fleet_free_pages`` is the same signal summed
        fleet-wide in :meth:`fleet_metrics`). When the request names a
        LoRA adapter, replicas with that adapter already RESIDENT in
        their device bank rank first (routing affinity saves a host→
        device row upload), engines built without a bank drop out
        entirely, and the same order breaks ties."""
        self.refresh_health()
        cands = [r for r in self._replicas
                 if r.state is ReplicaState.HEALTHY and r.engine.healthy
                 and (adapter is None or r.engine.adapters is not None)]

        def _pages_key(r):
            return (r.engine.page_deficit(total_tokens), -r.engine.free_pages)

        if adapter is None:
            cands.sort(key=lambda r: (-r.engine.free_slots, r.engine.load,
                                      *_pages_key(r), r.index))
        else:
            cands.sort(key=lambda r: (not r.engine.adapter_resident(adapter),
                                      -r.engine.free_slots, r.engine.load,
                                      *_pages_key(r), r.index))
        return cands

    def submit(self, prompt_ids=None, *, max_new_tokens: int = 20,
               seed: Optional[int] = None, rng=None,
               timeout: Optional[float] = None, on_token=None,
               ignore_eos: bool = False, adapter: Optional[str] = None,
               trace_id: Optional[str] = None,
               block: bool = False,
               block_timeout: Optional[float] = None) -> FleetRequest:
        """Route one request to the least-loaded healthy replica; returns
        a :class:`FleetRequest` immediately. Raises
        :class:`~.scheduler.QueueFull` when every healthy replica's
        admission queue is full (``block=True`` waits for space on the
        best one first, up to ``block_timeout``), ``RuntimeError`` when no
        replica is healthy at all, and ``LookupError``
        (:class:`~..adapters.registry.UnknownAdapterError`) when
        ``adapter`` names an adapter no healthy replica has registered —
        the signal the gateway maps to HTTP 404."""
        fleet = FleetRequest(prompt_ids, max_new_tokens=max_new_tokens,
                             rng=rng, seed=seed, timeout=timeout,
                             on_token=on_token, ignore_eos=ignore_eos,
                             adapter=adapter, trace_id=trace_id)
        fleet.submitted_at = time.monotonic()
        with self._lock:
            self._submitted += 1
        self._dispatch(fleet, block=block, block_timeout=block_timeout)
        return fleet

    def _dispatch(self, fleet: FleetRequest, *, block: bool,
                  block_timeout: Optional[float], _raise: bool = True):
        """Try candidates best-first with non-blocking submits; only after
        ALL are queue-full does ``block=True`` wait on the current best.
        With ``_raise=False`` (failover path, running on a dead engine's
        thread) failures finish the fleet request instead of raising."""
        last_exc: Optional[BaseException] = None
        saturated = False
        # Page-aware score input: tokens this request will occupy (prompt +
        # already-generated on failover resume + remaining decode budget).
        total_tokens = (int(fleet.prompt_ids.shape[1]) + len(fleet.tokens)
                        + int(fleet.max_new_tokens))
        for attempt in range(2):
            for r in self._candidates(fleet.adapter, total_tokens=total_tokens):
                inner = self._make_inner(fleet, r)
                if inner is None:  # cancelled or deadline passed meanwhile
                    return
                try:
                    r.engine.submit(
                        request=inner,
                        block=block and attempt > 0,
                        block_timeout=block_timeout)
                except QueueFull as e:
                    last_exc, saturated = e, True
                    continue
                except LookupError as e:
                    # THIS replica's registry doesn't know the adapter
                    # (registries may trail during a rollout) — try the
                    # next one; when nobody knows, the LookupError
                    # surfaces to the caller as-is (gateway → 404).
                    last_exc = e
                    continue
                except RuntimeError as e:
                    # Died between the health check and the enqueue.
                    last_exc = e
                    self._fence(r)
                    continue
                with fleet._lock:
                    fleet._inner = inner
                fleet.replica_trail.append(r.index)
                if fleet.cancel_requested:
                    inner.cancel()  # cancel raced the dispatch
                return
            if not (block and saturated):
                break
        if _raise:
            if saturated:
                raise QueueFull(
                    "every healthy replica's admission queue is full; "
                    "retry later") from last_exc
            if isinstance(last_exc, LookupError):
                raise last_exc
            raise RuntimeError(
                "no healthy replica available") from last_exc
        with self._lock:
            self._failover_failed += 1
        fleet._finish(RequestStatus.FAILED, RuntimeError(
            "failover found no healthy replica with queue space")
            if last_exc is None else last_exc)

    def _make_inner(self, fleet: FleetRequest,
                    replica: _Replica) -> Optional[Request]:
        """Build the next flight: the remaining-budget request whose prompt
        is ``original + emitted`` (so token budgets, deadline, and KV
        occupancy all add up to exactly the uninterrupted request's)."""
        if fleet.cancel_requested:
            fleet._finish(RequestStatus.CANCELLED)
            return None
        remaining_t = fleet._remaining_timeout()
        if remaining_t is not None and remaining_t <= 0:
            fleet._finish(RequestStatus.TIMED_OUT)
            return None
        inner = Request(fleet._resume_prompt(),
                        max_new_tokens=fleet._remaining_new_tokens(),
                        rng=fleet.rng, seed=fleet.seed,
                        timeout=remaining_t, on_token=fleet._emit,
                        ignore_eos=fleet.ignore_eos,
                        adapter=fleet.adapter,
                        trace_id=fleet.trace_id)
        inner._on_finish = lambda req: self._on_inner_finish(
            fleet, replica, req)
        return inner

    # -- adapters ---------------------------------------------------------
    def register_adapter(self, name: str, adapter, **kwargs):
        """Register a LoRA adapter on EVERY replica's bank. Fleet-wide
        registration is what makes failover tenant-preserving: a stream
        decoding under adapter X can resume on any survivor, which loads
        X into its own bank at admission if it isn't already resident.
        Raises ``RuntimeError`` if any replica was built without an
        :class:`~..adapters.registry.AdapterBank`."""
        for r in self._replicas:
            r.engine.register_adapter(name, adapter, **kwargs)

    def unregister_adapter(self, name: str):
        """Drop a named adapter from every replica that knows it (idle
        banks only free the device row lazily on the next eviction)."""
        for r in self._replicas:
            bank = r.engine.adapters
            if bank is not None and name in bank.names():
                bank.unregister(name)

    # -- failover ---------------------------------------------------------
    def _on_inner_finish(self, fleet: FleetRequest, replica: _Replica,
                         inner: Request):
        """Runs ON THE ENGINE THREAD at the inner request's terminal
        transition. Engine-death failures fence the replica and resubmit;
        everything else (completion, cancellation, deadline, a raising
        user callback) passes through to the fleet handle."""
        if inner.status is RequestStatus.FAILED \
                and replica.engine.error is not None \
                and not fleet.cancel_requested:
            self._fence(replica)
            # Attach the dead replica's postmortem (its engine froze the
            # flight-recorder dump — fatal event included — before this
            # retire sweep started) so the hop is debuggable after the
            # fact without the replica.
            report = {
                "trace_id": fleet.trace_id,
                "replica": replica.index,
                "error": repr(replica.engine.error),
                "tokens_at_failover": len(fleet.tokens),
                "flight_recorder": replica.engine.postmortem(),
            }
            with self._lock:
                self._failover_reports.append(report)
                del self._failover_reports[:-32]  # keep the last 32 hops
            if fleet.failovers >= self._max_failovers:
                fleet._finish(RequestStatus.FAILED, RuntimeError(
                    f"request failed over {fleet.failovers} times "
                    "(max_failovers reached)"))
                return
            with self._lock:
                self._failovers += 1
                replica.failures += 1
            self._dispatch(fleet, block=True,
                           block_timeout=self._failover_block_s,
                           _raise=False)
            return
        fleet._finish(inner.status, inner.error)

    @property
    def failover_reports(self) -> list[dict]:
        """Postmortems for the most recent failover hops (newest last):
        ``{trace_id, replica, error, tokens_at_failover, flight_recorder}``
        where ``flight_recorder`` is the dead engine's frozen event dump
        (fatal event included). Bounded to the last 32 hops."""
        with self._lock:
            return list(self._failover_reports)

    def chrome_trace(self, trace_id: Optional[str] = None) -> dict:
        """One fleet-wide Chrome-trace dict: every replica's buffered
        spans (optionally filtered to one ``trace_id``) merged onto the
        shared monotonic timeline — a failed-over request shows its
        replica-A spans next to its replica-B continuation. Backs the
        gateway's ``GET /debug/trace``."""
        from ..observability import merge_chrome_traces

        return merge_chrome_traces(
            r.engine.chrome_trace(trace_id) for r in self._replicas)

    # -- metrics ----------------------------------------------------------
    def merged_stats(self) -> ServingStats:
        """A fresh :class:`ServingStats` holding the fleet-wide fold of
        every replica's counters (see ``ServingStats.merge``)."""
        merged = ServingStats()
        for r in self._replicas:
            merged.merge(r.engine.stats)
        return merged

    def fleet_metrics(self) -> dict:
        """Merged engine summary plus router-level counters (replica
        states, failover/fence counts) — the dict behind ``/metrics``."""
        self.refresh_health()
        out = self.merged_stats().summary()
        states = [r.state for r in self._replicas]
        with self._lock:
            out.update({
                "replicas": len(self._replicas),
                "replicas_healthy": sum(
                    s is ReplicaState.HEALTHY for s in states),
                "replicas_draining": sum(
                    s is ReplicaState.DRAINING for s in states),
                "replicas_failed": sum(
                    s is ReplicaState.FAILED for s in states),
                "fleet_submitted": self._submitted,
                "fleet_failovers": self._failovers,
                "fleet_fences": self._fences,
                "fleet_failover_failed": self._failover_failed,
                "fleet_free_slots": sum(
                    r.engine.free_slots for r in self._replicas
                    if r.state is ReplicaState.HEALTHY and r.engine.healthy),
                # Paged-KV headroom across the healthy fleet (0 when every
                # replica is dense). Page pressure already steers routing
                # through ``engine.load``; this is the operator's view.
                "fleet_free_pages": sum(
                    r.engine.free_pages for r in self._replicas
                    if r.state is ReplicaState.HEALTHY and r.engine.healthy),
            })
        return out

    # -- lifecycle --------------------------------------------------------
    def drain(self):
        """Stop routing new work everywhere (all HEALTHY → DRAINING);
        in-flight streams keep running. The gateway's SIGTERM path."""
        for r in self._replicas:
            if r.state is ReplicaState.HEALTHY:
                r.state = ReplicaState.DRAINING

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Shut every replica down (``drain=True`` finishes accepted work
        first). Replicas that already died are fenced, not re-raised —
        their error was already delivered to their requests."""
        first_exc: Optional[BaseException] = None
        for r in self._replicas:
            try:
                r.engine.shutdown(drain=drain, timeout=timeout)
            except RuntimeError as e:
                self._fence(r)
                if r.engine.error is None and first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)
