"""Admission queue, slot bookkeeping, and the prefix KV block cache for
the serving engine.

Three small host-side structures, deliberately independent of jax:

* :class:`AdmissionQueue` — a bounded queue with backpressure: FCFS by
  default, a PRIORITY queue (strict class order, FIFO within class) when
  built with a ``rank_fn`` (see :class:`~.control.PriorityPolicy`). The
  bound is the engine's only flow control: when the queue is full,
  ``submit`` either raises :class:`QueueFull` (``block=False``) or blocks
  the caller until the engine drains a request (``block=True``), so a
  burst of traffic turns into caller-side latency instead of unbounded
  host memory.
* :class:`SlotScheduler` — a free-list over the fixed ``max_slots`` decode
  lanes. FCFS: the engine pops the oldest queued request whenever a slot
  is free. Slots are plain integers; all per-slot device state lives in
  the engine's state pytree, indexed by these.
* :class:`PrefixCache` — a byte-bounded LRU of chunk-aligned KV blocks
  keyed by the engine's prompt-prefix hash chain. The values are opaque
  here (device-array pytrees the engine's ``restore_prefix`` program
  copies back into a slot); the caller supplies each entry's byte size so
  this module stays jax-free.
* :class:`PagePool` — a free-list + refcount table over the paged
  engine's global KV page pool. Pages are plain integers indexing the
  device-side page arrays; refcounts exist because prefix-cache aliasing
  lets one physical page appear in several slots' page tables (and in
  the cache itself) at once. Engine-thread only, like
  :class:`SlotScheduler`.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Optional

from .request import Request


class QueueFull(RuntimeError):
    """Raised by non-blocking submit when the admission queue is at bound."""


class QueueClosed(RuntimeError):
    """Raised by ``put`` when the engine behind the queue has stopped — the
    request can never be served, so the caller (blocked or not) is woken
    with this instead of enqueueing onto (or hanging against) a dead
    engine."""


class AdmissionQueue:
    """Bounded request queue (thread-safe; many producers, one engine
    consumer). FCFS by default; pass ``rank_fn`` to make it a PRIORITY
    queue — strict rank order across classes (lower rank pops first,
    so interactive traffic admits ahead of queued batch work), FIFO
    within each class.

    Built on a condition pair rather than ``queue.Queue`` so the consumer
    can :meth:`close` it: a producer blocked in ``put(block=True)`` against
    a full queue is woken with :class:`QueueClosed` the moment the engine
    stops, instead of sleeping forever on space that will never free.

    Args:
      max_queued: the bound (the engine's only flow control).
      rank_fn: maps a request's ``priority`` (a string or None) to an
        integer rank, 0 = most important — typically
        :meth:`~.control.PriorityPolicy.rank`. ``None`` (default) ranks
        everything equal, which is exactly the old FCFS behavior.
    """

    def __init__(self, max_queued: int = 64, rank_fn=None):
        if max_queued < 1:
            raise ValueError(f"max_queued must be >= 1 (got {max_queued})")
        self.max_queued = int(max_queued)
        self._rank_fn = rank_fn
        # rank -> FIFO deque; gets scan ranks ascending. With rank_fn=None
        # everything lands in bucket 0 and this IS a plain FIFO deque.
        self._buckets: dict[int, collections.deque[Request]] = {}
        self._n = 0
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._pending_tokens = 0

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending_tokens(self) -> int:
        """Projected token footprint (prompt + max_new) summed over every
        queued request — the admission-side half of the gateway's
        projected-pressure shed signal. For a preempted request requeued
        by ``putleft`` this over-counts by the tokens it already emitted;
        pressure estimates only need an upper bound."""
        return self._pending_tokens

    @staticmethod
    def _footprint(request: Request) -> int:
        # Tolerate non-Request items: lifecycle unit tests (and any future
        # sentinel objects) flow through the queue without a footprint.
        try:
            return (int(request.prompt_ids.shape[1])
                    + int(request.max_new_tokens))
        except AttributeError:
            return 0

    def _rank_of(self, request) -> int:
        if self._rank_fn is None:
            return 0
        return int(self._rank_fn(getattr(request, "priority", None)))

    def _bucket(self, rank: int) -> collections.deque:
        bucket = self._buckets.get(rank)
        if bucket is None:
            bucket = self._buckets[rank] = collections.deque()
        return bucket

    def put(self, request: Request, block: bool = True,
            timeout: Optional[float] = None):
        """Enqueue; raises :class:`QueueFull` on backpressure (immediately
        when ``block=False``, after ``timeout`` otherwise) and
        :class:`QueueClosed` — immediately, or mid-wait — once the engine
        has stopped."""
        with self._lock:
            deadline = None if timeout is None else time.monotonic() + timeout
            while True:
                if self._closed:
                    raise QueueClosed(
                        "serving engine stopped; the admission queue is "
                        "closed and will never drain")
                if self._n < self.max_queued:
                    self._bucket(self._rank_of(request)).append(request)
                    self._n += 1
                    self._pending_tokens += self._footprint(request)
                    self._not_empty.notify()
                    return
                if not block:
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._not_full.wait(remaining)
            raise QueueFull(
                f"admission queue full ({self.max_queued} requests queued); "
                "retry later or submit with block=True")

    def putleft(self, request: Request):
        """Requeue at the FRONT of the request's class, bypassing the
        bound — the paged engine's preemption path: a request evicted
        from its slot on pool exhaustion goes back ahead of everything
        younger IN ITS CLASS (it was admitted first; within-class FCFS
        order is preserved, not reset — but it never jumps a class the
        priority policy ranks above it), and it must never bounce off a
        momentarily-full queue it already passed through."""
        with self._lock:
            if self._closed:
                raise QueueClosed(
                    "serving engine stopped; the admission queue is "
                    "closed and will never drain")
            self._bucket(self._rank_of(request)).appendleft(request)
            self._n += 1
            self._pending_tokens += self._footprint(request)
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None) -> Optional[Request]:
        """Pop the best-ranked oldest request, or None after ``timeout``
        (engine poll). Close does not interrupt gets — the engine keeps
        draining what is already queued during shutdown."""
        with self._lock:
            if not self._n and timeout is not None and timeout > 0:
                self._not_empty.wait(timeout)
            if not self._n:
                return None
            for rank in sorted(self._buckets):
                bucket = self._buckets[rank]
                if bucket:
                    item = bucket.popleft()
                    break
            self._n -= 1
            self._pending_tokens -= self._footprint(item)
            self._not_full.notify()
            return item

    def get_nowait(self) -> Optional[Request]:
        return self.get()

    def close(self):
        """Mark the queue dead (engine stopped) and wake every producer
        blocked in ``put`` with :class:`QueueClosed`. Items already queued
        stay poppable so the shutdown path can drain and finish them."""
        with self._lock:
            self._closed = True
            self._not_full.notify_all()
            self._not_empty.notify_all()

    def __len__(self) -> int:
        return self._n

    def drain(self) -> list[Request]:
        """Remove and return everything currently queued (shutdown path)."""
        out = []
        while True:
            r = self.get_nowait()
            if r is None:
                return out
            out.append(r)


class SlotScheduler:
    """Free-list of decode slots + the request occupying each.

    Engine-thread only (no lock): admission, retirement, and the tick loop
    all run on the single engine thread.
    """

    def __init__(self, max_slots: int):
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1 (got {max_slots})")
        self.max_slots = int(max_slots)
        self._free = collections.deque(range(self.max_slots))
        self._occupant: dict[int, Request] = {}

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def active_slots(self) -> int:
        return len(self._occupant)

    def has_free(self) -> bool:
        return bool(self._free)

    def assign(self, request: Request) -> int:
        slot = self._free.popleft()  # lowest-index-first keeps state compact
        self._occupant[slot] = request
        request.slot = slot
        return slot

    def release(self, slot: int) -> Request:
        request = self._occupant.pop(slot)
        request.slot = None
        self._free.append(slot)
        return request

    def occupant(self, slot: int) -> Optional[Request]:
        return self._occupant.get(slot)

    def active(self) -> list[tuple[int, Request]]:
        """(slot, request) pairs for every occupied slot, slot-ordered."""
        return sorted(self._occupant.items())


class PagePool:
    """Free-list + refcounts over the paged engine's fixed-size KV pages.

    Page ids are ``1..num_pages``; page ``0`` is the engine's reserved
    scratch page (never allocated — the compiled programs route writes of
    released or not-yet-allocated slots there, so it holds garbage by
    design and is excluded from accounting here). A page's refcount is
    the number of owners keeping it alive: each slot whose page table
    holds it counts one, and a prefix-cache alias entry counts one more —
    the page returns to the free list only when the LAST owner drops it.
    Engine-thread only (no lock), like :class:`SlotScheduler`.
    """

    def __init__(self, num_pages: int):
        if num_pages < 1:
            raise ValueError(f"num_pages must be >= 1 (got {num_pages})")
        self.num_pages = int(num_pages)
        self._free: collections.deque[int] = collections.deque(
            range(1, self.num_pages + 1))
        self._ref = [0] * (self.num_pages + 1)
        self.allocations = 0
        self.preemptions = 0  # billed by the engine when exhaustion preempts
        self.frees = 0  # pages returned to the free list (drain-rate input)

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def used_pages(self) -> int:
        return self.num_pages - len(self._free)

    def alloc(self) -> Optional[int]:
        """Pop one free page (refcount 1), or None when the pool is
        exhausted — the engine then reclaims alias-held pages or preempts
        a slot; allocation itself never blocks or raises."""
        if not self._free:
            return None
        page = self._free.popleft()
        self._ref[page] = 1
        self.allocations += 1
        return page

    def incref(self, page: int):
        """One more owner for an allocated page (prefix aliasing: a cache
        entry, or a second slot's table row, now also points at it)."""
        if page <= 0 or self._ref[page] <= 0:
            raise ValueError(f"incref of unallocated page {page}")
        self._ref[page] += 1

    def decref(self, page: int) -> bool:
        """Drop one owner; returns True when this freed the page."""
        if page <= 0 or self._ref[page] <= 0:
            raise ValueError(f"decref of unallocated page {page}")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            self._free.append(page)
            self.frees += 1
            return True
        return False

    def refcount(self, page: int) -> int:
        return self._ref[page]


class PrefixCache:
    """Byte-bounded LRU of chunk-aligned prefix KV blocks.

    Keys are hash-chain digests: the engine hashes each chunk's tokens
    TOGETHER with the previous chunk's digest, so a key identifies the
    entire token prefix up to and including its chunk — two prompts share
    an entry exactly when they share that whole chunk-aligned prefix.
    Values are opaque (device-array pytrees holding one chunk's KV slice
    for every cache leaf — or host numpy trees for mesh-sliced engines,
    which is what makes the blocks portable ACROSS slices); the engine
    passes each block's byte size into :meth:`put` so accounting stays
    jax-free here.

    Thread-safe: unlike :class:`SlotScheduler`, one instance may be shared
    by every slice of a ``ReplicaSet.from_mesh`` fleet (each slice engine
    reads and writes from its own engine thread), so a prefix one slice
    prefilled is a hit on any other — including the failover resume path.
    The lock covers each operation; blocks themselves are immutable once
    inserted.
    """

    def __init__(self, capacity_bytes: int, on_evict=None):
        if capacity_bytes < 1:
            raise ValueError(
                f"capacity_bytes must be >= 1 (got {capacity_bytes}); "
                "disable prefix caching at the engine instead")
        self.capacity_bytes = int(capacity_bytes)
        # key -> (block, nbytes); insertion order == LRU order (move_to_end
        # on every touch), so eviction pops from the front.
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()
        self._bytes = 0
        self.insertions = 0
        self.evictions = 0
        self.oversize_rejects = 0
        #: ``on_evict(key, block)`` fires (lock held) whenever an entry
        #: leaves the cache — eviction, reclaim, or clear. The paged engine
        #: uses it to drop the PagePool refs its alias entries hold; the
        #: default copy-block cache needs no hook.
        self._on_evict = on_evict

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return self._bytes

    def match(self, keys) -> list:
        """Blocks for the longest cached prefix of ``keys``, in chain order
        (each hit is touched most-recently-used). Stops at the first miss:
        a later chunk's KV is only valid on top of every earlier one."""
        out = []
        with self._lock:
            for key in keys:
                entry = self._entries.get(key)
                if entry is None:
                    break
                self._entries.move_to_end(key)
                out.append(entry[0])
        return out

    def longest_prefix(self, keys) -> int:
        """How many leading ``keys`` are resident, WITHOUT touching LRU
        order or refcounts — the cheap probe behind prefix-cache-aware
        routing (:meth:`~.router.ReplicaSet._candidates` calls it per
        candidate replica per routing decision, so it must not promote
        entries a request may never actually restore). Stops at the
        first miss for the same chain reason :meth:`match` does."""
        n = 0
        with self._lock:
            for key in keys:
                if key not in self._entries:
                    break
                n += 1
        return n

    def put(self, key, block, nbytes: int) -> bool:
        """Insert one chunk's block (touch if already present), then evict
        least-recently-used entries until within capacity. A block larger
        than the whole capacity is rejected outright — admitting it would
        evict EVERY resident entry and still not fit, so the cache keeps
        what it has and counts the reject instead. Returns True only when
        the block was actually inserted (the paged engine pins page refs
        per INSERTED entry, so touch/reject must be distinguishable)."""
        with self._lock:
            if key in self._entries:
                self._entries.move_to_end(key)
                return False
            nbytes = int(nbytes)
            if nbytes > self.capacity_bytes:
                self.oversize_rejects += 1
                return False
            self._entries[key] = (block, nbytes)
            self._bytes += nbytes
            self.insertions += 1
            while self._bytes > self.capacity_bytes:
                self._pop_lru_locked()
            return True

    def _pop_lru_locked(self):
        key, (block, nb) = self._entries.popitem(last=False)
        self._bytes -= nb
        self.evictions += 1
        if self._on_evict is not None:
            self._on_evict(key, block)

    def evict_lru(self) -> bool:
        """Force out the least-recently-used entry (False when empty) —
        the paged engine's reclaim path: alias-held pages are freed
        cache-entry by cache-entry until an allocation succeeds, BEFORE
        any running request gets preempted."""
        with self._lock:
            if not self._entries:
                return False
            self._pop_lru_locked()
            return True

    def entries(self) -> list:
        """(key, block) snapshot in LRU order (reclaimability accounting:
        the paged engine counts pages whose only owner is the cache)."""
        with self._lock:
            return [(k, b) for k, (b, _) in self._entries.items()]

    def clear(self):
        """Drop every entry (engine warmup runs dummy prompts through the
        normal path; their blocks must not linger as phantom prefixes)."""
        with self._lock:
            if self._on_evict is not None:
                for key, (block, _) in self._entries.items():
                    self._on_evict(key, block)
            self._entries.clear()
            self._bytes = 0
            self.insertions = 0
            self.evictions = 0
            self.oversize_rejects = 0
