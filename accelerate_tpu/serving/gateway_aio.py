"""Asyncio front end for :class:`~.gateway.ServingGateway`.

One OS thread, one event loop, thousands of concurrent SSE streams.
``ThreadingHTTPServer`` parks a whole thread (stack + scheduler slot)
on every open connection, which caps a gateway process at hundreds of
streams; here every connection is a coroutine and an open-but-idle SSE
stream costs a few KB of heap, so the same process multiplexes
thousands. The engine-facing side stays exactly as it was — threads:

* tokens cross from the engines' emitter threads onto the loop via
  ``loop.call_soon_threadsafe`` into a bounded per-stream
  :class:`asyncio.Queue` (:class:`_StreamBridge`; overflow spills to an
  ordered side deque touched only on the loop thread, so no token is
  ever dropped or reordered — the engine's own bounded
  ``emission_queue`` is the upstream flow control);
* request completion rides :meth:`~.router.FleetRequest
  .add_done_callback`, so no coroutine ever blocks the loop in
  ``FleetRequest.wait``. ``call_soon_threadsafe`` is FIFO per loop, and
  the engine emits every token before it finishes the request, so the
  done sentinel always lands *after* the last token.

The HTTP surface is deliberately identical to the threading front end
— same routes, same status-code mapping, same drain semantics — which
is enforced by sharing the admission path (``ServingGateway
.submit_or_error``) and the body parser / response shapers
(``parse_completion`` / ``summary_payload`` / ``completion_result``)
rather than by duplicated code. The server object duck-types the
``ThreadingHTTPServer`` surface the gateway lifecycle drives
(``server_address``, ``shutdown()``, ``server_close()``) plus a
``thread`` attribute.

Stdlib-only on purpose (``asyncio`` + streams): the repo takes no HTTP
framework dependency, and a minimal HTTP/1.1 parser (request line,
headers, ``Content-Length`` framing, keep-alive) is all the gateway
protocol needs.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import threading
from collections import deque
from http.client import responses as _HTTP_REASONS
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..observability import clean_trace_id, new_trace_id
from .gateway import (_STATUS_HTTP, _BadRequest, completion_result,
                      parse_completion, summary_payload)
from .request import RequestStatus

__all__ = ["AsyncioGatewayServer"]


class _StreamBridge:
    """Engine-thread → event-loop token conduit for one SSE stream.

    ``push_threadsafe`` is the ``on_token`` callback (runs on an engine
    emitter thread — must never block, or it head-of-line-blocks every
    stream that emitter serves); it hops onto the loop where ``_push``
    enqueues into a bounded :class:`asyncio.Queue`, spilling to an
    ordered deque when a slow client has let the queue fill. Queue and
    deque are touched only on the loop thread, so there is no lock and
    no race. ``finish_threadsafe`` rides the same FIFO, so the DONE
    sentinel is always delivered after every token that preceded it.
    """

    DONE = object()

    def __init__(self, loop: asyncio.AbstractEventLoop, maxsize: int):
        self._loop = loop
        self._q: asyncio.Queue = asyncio.Queue(maxsize)
        self._overflow: deque = deque()  # loop-thread only

    # -- engine side ------------------------------------------------------
    def push_threadsafe(self, tok):
        try:
            self._loop.call_soon_threadsafe(self._push, int(tok))
        except RuntimeError:
            pass  # loop closed mid-shutdown; the stream is dead anyway

    def finish_threadsafe(self, _fleet=None):
        try:
            self._loop.call_soon_threadsafe(self._push, self.DONE)
        except RuntimeError:
            pass

    # -- loop side --------------------------------------------------------
    def _push(self, item):
        if self._overflow or self._q.full():
            self._overflow.append(item)  # strict arrival order
        else:
            self._q.put_nowait(item)

    async def get(self):
        item = await self._q.get()
        while self._overflow and not self._q.full():
            self._q.put_nowait(self._overflow.popleft())
        return item


class AsyncioGatewayServer:
    """Event-loop HTTP front end behind ``ServingGateway``.

    Constructed by ``ServingGateway.start()`` when
    ``config.server == "asyncio"``; binds synchronously (the
    constructor returns with ``server_address`` resolved, or raises the
    bind error) and serves from a daemon thread running the loop.
    """

    def __init__(self, gateway):
        self.gateway = gateway
        self._loop = asyncio.new_event_loop()
        self._aio_server: Optional[asyncio.AbstractServer] = None
        self.server_address: Optional[tuple] = None
        self._started = threading.Event()
        self._startup_error: Optional[BaseException] = None
        self.thread = threading.Thread(
            target=self._run, name="serving-gateway-aio", daemon=True)
        self.thread.start()
        if not self._started.wait(timeout=30):
            raise RuntimeError("asyncio gateway did not bind within 30s")
        if self._startup_error is not None:
            raise self._startup_error

    # -- lifecycle (ThreadingHTTPServer duck-type) ------------------------
    def _run(self):
        asyncio.set_event_loop(self._loop)
        try:
            try:
                self._aio_server = self._loop.run_until_complete(
                    asyncio.start_server(
                        self._handle_conn, self.gateway.config.host,
                        self.gateway.config.port, backlog=2048))
                self.server_address = (
                    self._aio_server.sockets[0].getsockname()[:2])
            except BaseException as e:  # bind errors surface in __init__
                self._startup_error = e
                return
            finally:
                self._started.set()
            self._loop.run_forever()
            # shutdown() stopped the loop: reap every open connection.
            pending = asyncio.all_tasks(self._loop)
            for t in pending:
                t.cancel()
            if pending:
                self._loop.run_until_complete(
                    asyncio.gather(*pending, return_exceptions=True))
            self._loop.run_until_complete(self._loop.shutdown_asyncgens())
        finally:
            self._loop.close()

    def shutdown(self):
        """Stop the listener, cancel open exchanges, join the loop
        thread. Idempotent; callable from any thread (the gateway's
        drain already waited for in-flight exchanges when graceful)."""
        if not self.thread.is_alive():
            return

        def _stop():
            if self._aio_server is not None:
                self._aio_server.close()
            self._loop.stop()

        with contextlib.suppress(RuntimeError):
            self._loop.call_soon_threadsafe(_stop)
        self.thread.join(timeout=10)

    def server_close(self):
        self.shutdown()

    # -- connection handling ----------------------------------------------
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_head(reader)
                if req is None:
                    break
                close = await self._dispatch(req, reader, writer)
                await writer.drain()
                if close:
                    break
        except (ConnectionError, TimeoutError, asyncio.IncompleteReadError):
            pass  # client went away mid-exchange
        finally:
            with contextlib.suppress(Exception):
                writer.close()

    async def _read_head(self, reader):
        """Parse one request head: ``(method, target, version, headers)``
        with header names lowercased, or None on EOF / malformed head
        (the connection just closes — matching ``http.server``, which
        clients see as a dropped keep-alive, not an error page)."""
        try:
            line = await reader.readline()
        except (ValueError, ConnectionError):
            return None  # over-long request line
        if not line or line in (b"\r\n", b"\n"):
            return None
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        method, target = parts[0], parts[1]
        version = parts[2] if len(parts) > 2 else "HTTP/1.0"
        headers = {}
        while True:
            try:
                h = await reader.readline()
            except (ValueError, ConnectionError):
                return None
            if h in (b"\r\n", b"\n", b""):
                break
            name, sep, value = h.decode("latin-1").partition(":")
            if sep:
                headers[name.strip().lower()] = value.strip()
        return method, target, version, headers

    async def _dispatch(self, req, reader, writer) -> bool:
        """Route one request; returns True when the connection must
        close (SSE, framing errors, explicit ``Connection: close``)."""
        method, target, version, headers = req
        conn_hdr = headers.get("connection", "").lower()
        close = (conn_hdr == "close"
                 or (version == "HTTP/1.0" and conn_hdr != "keep-alive"))
        gw = self.gateway
        parsed = urlparse(target)
        path = parsed.path
        if method == "GET":
            if not self._conn_enter(writer, path):
                return True
            try:
                if path == "/healthz":
                    self._send_text(writer, 200, "ok\n", "/healthz")
                elif path == "/readyz":
                    if gw.ready:
                        self._send_text(writer, 200, "ready\n", "/readyz")
                    else:
                        if gw.draining:
                            body = "draining\n"
                        else:
                            fm = gw.replica_set.fleet_metrics()
                            looped = int(fm.get("replicas_crash_loop", 0))
                            body = ("no healthy replica"
                                    + (f" ({looped} crash-looped)" if looped
                                       else "") + "\n")
                        self._send_text(writer, 503, body, "/readyz",
                                        extra_headers=self._retry_after())
                elif path == "/metrics":
                    self._send_text(
                        writer, 200, gw.metrics_text(), "/metrics",
                        content_type="text/plain; version=0.0.4; "
                                     "charset=utf-8")
                elif path == "/debug/trace":
                    self._debug_trace(writer, parse_qs(parsed.query))
                else:
                    self._send_json(writer, 404, {"error": "not found"},
                                    path)
            finally:
                self._conn_exit()
            return close
        if method == "POST":
            if path != "/v1/completions":
                self._send_json(writer, 404, {"error": "not found"}, path)
                return close
            return await self._completions(reader, writer, headers, close)
        self._send_json(writer, 501,
                        {"error": f"unsupported method {method}"}, path)
        return True

    def _debug_trace(self, writer, query: dict):
        route = "/debug/trace"
        raw = (query.get("id") or [None])[0]
        tid = None
        if raw is not None:
            tid = clean_trace_id(raw)
            if tid is None:
                self._send_json(writer, 400, {"error": "invalid trace id"},
                                route)
                return
        trace = self.gateway.replica_set.chrome_trace(tid)
        if tid is not None and not any(
                ev.get("ph") != "M" for ev in trace["traceEvents"]):
            self._send_json(writer, 404, {"error": "trace not found",
                                          "trace_id": tid}, route)
            return
        self._send_text(writer, 200, json.dumps(trace), route,
                        content_type="application/json")

    # -- completions -------------------------------------------------------
    async def _completions(self, reader, writer, headers,
                           close: bool) -> bool:
        gw = self.gateway
        route = "/v1/completions"
        if not self._conn_enter(writer, route):
            return True
        # Minted before anything can fail so even a 4xx/5xx body carries
        # a correlation id (the client's own X-Request-Id when valid).
        trace_id = (clean_trace_id(headers.get("x-request-id"))
                    or new_trace_id())
        try:
            if gw.draining:
                self._send_json(writer, 503, {"error": "gateway draining"},
                                route, extra_headers=self._retry_after(),
                                trace_id=trace_id)
                return close
            try:
                length = int(headers.get("content-length", ""))
            except ValueError:
                # No framing: the body (if any) is unreadable -> close.
                self._send_json(writer, 400,
                                {"error": "Content-Length required"},
                                route, trace_id=trace_id)
                return True
            if length > gw.config.max_body_bytes:
                # Refused BEFORE reading the body into memory; the bytes
                # are still on the socket, so the connection closes.
                self._send_json(
                    writer, 413,
                    {"error": f"request body {length} bytes exceeds "
                              f"max_body_bytes ({gw.config.max_body_bytes})"},
                    route, trace_id=trace_id)
                return True
            raw = await reader.readexactly(length)
            try:
                body = json.loads(raw)
                if not isinstance(body, dict):
                    raise _BadRequest("request body must be a JSON object")
                spec = parse_completion(body, gw.config)
            except json.JSONDecodeError as e:
                self._send_json(writer, 400,
                                {"error": f"invalid JSON: {e}"},
                                route, trace_id=trace_id)
                return close
            except _BadRequest as e:
                self._send_json(writer, 400, {"error": str(e)}, route,
                                trace_id=trace_id)
                return close
            stream = spec.pop("stream")
            if stream:
                await self._stream_sse(reader, writer, spec, trace_id,
                                       length)
                return True  # SSE is EOF-terminated
            fleet, err = gw.submit_or_error(spec, trace_id)
            if err is not None:
                code, payload, hdrs = err
                self._send_json(writer, code, payload, route,
                                extra_headers=hdrs, body_bytes_in=length,
                                trace_id=trace_id)
                return close
            done_ev = asyncio.Event()
            fleet.add_done_callback(
                lambda _f: self._call_soon(done_ev.set))
            await done_ev.wait()  # deadline enforced engine-side (408)
            code, payload, hdrs = completion_result(
                fleet, gw.config.retry_after_s)
            self._send_json(writer, code, payload, route,
                            extra_headers=hdrs, body_bytes_in=length,
                            trace_id=trace_id)
            return close
        finally:
            self._conn_exit()

    async def _stream_sse(self, reader, writer, spec: dict, trace_id: str,
                          nbytes: int):
        """One SSE event per token, a final summary event, EOF. A broken
        client socket (detected by the parked ``reader.read``) cancels
        the request so its slot frees at the next scheduler pass. With
        ``sse_heartbeat_s`` set, ``: ping`` comment frames keep
        intermediaries from severing streams parked in a deep backlog."""
        gw = self.gateway
        route = "/v1/completions"
        bridge = _StreamBridge(self._loop, gw.config.stream_queue_tokens)
        fleet, err = gw.submit_or_error(spec, trace_id,
                                        on_token=bridge.push_threadsafe)
        if err is not None:
            code, payload, hdrs = err
            self._send_json(writer, code, payload, route,
                            extra_headers=hdrs, body_bytes_in=nbytes,
                            trace_id=trace_id)
            return
        fleet.add_done_callback(bridge.finish_threadsafe)
        writer.write((
            "HTTP/1.1 200 OK\r\n"
            "Content-Type: text/event-stream\r\n"
            "Cache-Control: no-cache\r\n"
            "Connection: close\r\n"
            f"X-Request-Id: {fleet.trace_id}\r\n\r\n").encode())
        heartbeat = gw.config.sse_heartbeat_s
        sent = 0
        code = 200
        # Parked read: resolves only when the client half-closes (b"").
        eof_task = self._loop.create_task(reader.read(1))
        get_task = None
        gw.stats.stream_enter()
        try:
            while True:
                if get_task is None:
                    get_task = self._loop.create_task(bridge.get())
                done, _ = await asyncio.wait(
                    {get_task, eof_task}, timeout=heartbeat,
                    return_when=asyncio.FIRST_COMPLETED)
                if get_task in done:
                    item = get_task.result()
                    get_task = None
                    if item is _StreamBridge.DONE:
                        break
                    writer.write(
                        f"data: {json.dumps({'token': item})}\n\n".encode())
                    await writer.drain()
                    sent += 1
                    continue
                if eof_task in done:
                    try:
                        stray = eof_task.result()
                    except Exception:
                        stray = b""
                    if stray:
                        # Pipelined bytes, not a hang-up; keep watching.
                        eof_task = self._loop.create_task(reader.read(1))
                        continue
                    fleet.cancel()
                    code = 499  # client closed; nothing more to write
                    return
                # Neither task fired within the heartbeat window.
                writer.write(b": ping\n\n")
                await writer.drain()
            code, status = _STATUS_HTTP[fleet.status]
            final = summary_payload(fleet, status)
            final["done"] = True
            if fleet.status is not RequestStatus.COMPLETED:
                final["error"] = (str(fleet.error)
                                  if fleet.error is not None else status)
            writer.write(f"data: {json.dumps(final)}\n\n".encode())
            await writer.drain()
        except ConnectionError:
            fleet.cancel()
            code = 499
        finally:
            for t in (get_task, eof_task):
                if t is not None:
                    t.cancel()
            gw.stats.stream_exit()
            gw.stats.record_response(route, code, body_bytes=nbytes)
            gw.stats.record_stream(sent)

    # -- plumbing ----------------------------------------------------------
    def _call_soon(self, fn, *args):
        try:
            self._loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:
            pass  # loop closed mid-shutdown

    def _retry_after(self) -> dict:
        return {"Retry-After": f"{self.gateway.config.retry_after_s:g}"}

    def _conn_enter(self, writer, route: str) -> bool:
        """Take an in-flight slot (the SAME semaphore the threading
        front end uses, so tests and operators see one knob); refuse
        with 503 — and close, shedding front-end state — at the cap."""
        if not self.gateway._conn_slots.acquire(blocking=False):
            self.gateway.stats.record_conn_rejection()
            self._send_json(writer, 503,
                            {"error": "connection limit reached"},
                            route, extra_headers=self._retry_after())
            return False
        self.gateway.stats.inflight_enter()
        return True

    def _conn_exit(self):
        self.gateway.stats.inflight_exit()
        self.gateway._conn_slots.release()

    def _send_json(self, writer, code: int, payload: dict, route: str, *,
                   extra_headers: Optional[dict] = None,
                   body_bytes_in: int = 0,
                   trace_id: Optional[str] = None):
        if trace_id is not None:
            # Correlation id rides both channels: the JSON body (clients
            # that log payloads) and the X-Request-Id header (proxies).
            payload.setdefault("trace_id", trace_id)
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if trace_id is not None:
            headers["X-Request-Id"] = trace_id
        headers.update(extra_headers or {})
        self._write_head(writer, code, headers, len(body))
        writer.write(body)
        self.gateway.stats.record_response(route, code,
                                           body_bytes=body_bytes_in)

    def _send_text(self, writer, code: int, text: str, route: str,
                   content_type: str = "text/plain; charset=utf-8",
                   extra_headers: Optional[dict] = None):
        body = text.encode()
        headers = {"Content-Type": content_type}
        headers.update(extra_headers or {})
        self._write_head(writer, code, headers, len(body))
        writer.write(body)
        self.gateway.stats.record_response(route, code)

    @staticmethod
    def _write_head(writer, code: int, headers: dict, content_length: int):
        reason = _HTTP_REASONS.get(code, "")
        lines = [f"HTTP/1.1 {code} {reason}"]
        lines.extend(f"{k}: {v}" for k, v in headers.items())
        lines.append(f"Content-Length: {content_length}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
