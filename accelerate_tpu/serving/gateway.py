"""Stdlib-only HTTP gateway over a :class:`~.router.ReplicaSet`.

The network front door of the serving stack, with TWO interchangeable
front ends behind one :class:`ServingGateway` API
(``GatewayConfig(server=...)``):

* ``server="asyncio"`` (default) — a single-threaded :mod:`asyncio`
  event loop (``gateway_aio``) multiplexing thousands of concurrent SSE
  streams per process. The engine-facing side stays thread-based;
  tokens cross from the engines' emitter threads onto the loop via
  ``loop.call_soon_threadsafe`` into bounded per-stream queues, and
  request completion rides :meth:`~.router.FleetRequest
  .add_done_callback` — no handler thread ever parks in ``wait()``.
* ``server="threading"`` — the original ``ThreadingHTTPServer`` (one
  handler thread per connection; the handlers only wait on queues and
  sockets, all model work stays on the engine threads). One OS thread
  per open stream caps it at hundreds of connections — kept for A/B
  comparison and for deployments where a proxy bounds concurrency.

Both front ends expose the same routes and speak the same HTTP:

* ``POST /v1/completions`` — JSON in, JSON out, or Server-Sent Events
  when ``"stream": true`` (one ``data:`` event per token as the engine
  commits it, then a final summary event). Prompts are token-id lists —
  the repo has no tokenizer dependency, and the serving tests need
  bit-exact comparison against offline ``generate`` anyway.
* ``GET /healthz`` — liveness: 200 while the process serves HTTP at all.
* ``GET /readyz`` — readiness: 200 only when the gateway is not draining
  AND at least one replica is healthy and warm; 503 otherwise. Wire this
  one into the load balancer.
* ``GET /metrics`` — Prometheus text exposition: the fleet-merged engine
  counters (``ServingStats.merge`` across replicas) plus real
  cumulative-bucket latency histograms, router health/failover counters,
  process-wide XLA compile counters, and the gateway's own HTTP counters.
* ``GET /debug/trace?id=<trace_id>`` — the fleet's buffered spans as
  Chrome-trace/Perfetto JSON (``id`` narrows to one request; the id is
  minted per request — or taken from the client's ``X-Request-Id``
  header — and echoed in every response body and header).

Backpressure and failure map onto HTTP status codes instead of queues
growing without bound: every healthy replica's admission queue full →
**429** with ``Retry-After``; projected KV-page demand of admitted +
queued work past the paged pools' headroom (and not clearing within
``shed_wait_s`` at the observed page-drain rate) → **429** whose
``Retry-After`` is *derived from that drain rate*, shedding work the
queues would accept and then time out on; a tenant over its token-bucket
rate limit (``rate_limits``) → **429** whose ``Retry-After`` is the
bucket's refill time; a tenant over its weighted fair share of in-flight
streams while the fleet is under pressure (``fair_share_weights``) →
**429**; all derived Retry-After values clamp into the shared
``[retry_after_s, retry_after_max_s]`` window; per-request deadline
expired → **408**;
request body over the cap → **413**; connection cap hit, gateway
draining, or no healthy replica → **503**; malformed request → **400**.
Multi-tenant LoRA maps the same way: ``"adapter"`` naming an adapter no
replica has registered → **404** ``unknown_adapter``; every bank row
pinned by an in-flight stream (momentary residency pressure) → **503**
``adapter_bank_full`` with ``Retry-After``.

Graceful drain: ``shutdown(drain=True)`` (also wired to SIGTERM/SIGINT
by :meth:`ServingGateway.install_signal_handlers`) flips the gateway to
draining — ``/readyz`` goes 503 so balancers stop sending, new
completions are refused with 503 — waits for in-flight HTTP exchanges
to finish, then drains the replicas themselves (which flushes any
pending async checkpoint saves; see ``ServingEngine.shutdown``).
"""

from __future__ import annotations

import json
import queue
import signal
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..adapters.registry import AdapterBankFull
from ..observability import clean_trace_id, new_trace_id
from .engine import ServingEngine
from .metrics import HISTOGRAM_NAMES, GatewayStats
from .request import RequestStatus
from .router import ReplicaSet
from .scheduler import QueueFull

__all__ = ["ServingGateway", "GatewayConfig"]


class GatewayConfig:
    """Knobs for the HTTP layer (the model/engine knobs live on the
    engines themselves).

    Args:
      server: which front end serves the HTTP: ``"asyncio"`` (default —
        one event loop multiplexing every connection) or ``"threading"``
        (one handler thread per connection). Same routes, same status
        codes, same drain semantics either way.
      host: bind address (default loopback — put a real proxy in front
        before binding wider).
      port: TCP port; **0 asks the OS for an ephemeral port** (read it
        back from ``gateway.port`` — this is what the tests use, so no
        fixed-port flakes).
      max_body_bytes: request bodies over this are refused with 413
        before being read into memory.
      max_connections: concurrent in-flight HTTP exchanges; past it new
        requests get 503 (the admission queues provide the real
        backpressure — this cap only bounds front-end state). ``None``
        picks a per-front-end default: 64 for threading (it is a THREAD
        cap there) vs 8192 for asyncio (an open socket costs a few KB,
        not a stack).
      sse_heartbeat_s: emit an SSE comment frame (``: ping``) on any
        stream that has written nothing for this many seconds (e.g.
        sitting deep in a PREFILLING backlog) so proxies and LBs don't
        sever long-queued streams as idle. ``None`` (default) disables
        — tests compare byte-exact SSE bodies.
      stream_queue_tokens: bound of the per-stream token queue between
        the engine's emitter thread and the front end (tokens buffered
        ahead of a slow client; overflow spills to an ordered side list
        so no token is ever dropped — the engine's own bounded
        ``emission_queue`` is the upstream flow control).
      default_max_new_tokens: used when a completion request omits
        ``max_new_tokens``.
      max_new_tokens_cap: hard per-request ceiling (400 past it);
        ``None`` defers entirely to the engines' ``max_len`` check.
      default_timeout_s: per-request deadline applied when the body
        omits ``timeout``; ``None`` means no deadline.
      retry_after_s: floor for the ``Retry-After`` header on 429/503
        (queue-full and drain refusals use it as-is).
      drain_grace_s: how long ``shutdown(drain=True)`` waits for
        in-flight HTTP exchanges before proceeding anyway.
      shed_projected_pressure: refuse (429) a completion whose projected
        KV-page demand — together with everything already admitted and
        queued — cannot be covered by the paged pools within
        ``shed_wait_s`` at the fleet's *observed* page-drain rate.
        This sheds load the queues would otherwise accept and then time
        out on. With no observed drain yet (cold start) or on dense
        engines nothing is shed.
      shed_wait_s: the pressure-shed horizon: admit as long as the
        projected page deficit clears within this many seconds of
        observed drain.
      retry_after_max_s: cap on the drain-rate-derived ``Retry-After``
        of a pressure shed (the floor is ``retry_after_s``); the same
        clamp bounds rate-limit Retry-After values.
      rate_limits: per-tenant token-bucket request rates — a dict
        ``{tenant: requests_per_s}`` keyed on adapter name (base-model
        traffic is tenant ``"_base"``; the ``"*"`` key sets a default
        for unlisted tenants). ``None`` (default) disables rate
        limiting. Refusals are structured 429s whose ``Retry-After``
        derives from the tenant bucket's refill time.
      rate_limit_burst_s: bucket capacity in seconds of budget — a
        tenant may burst ``rate * burst_s`` requests after idling.
      fair_share_weights: weighted fair-share admission over in-flight
        streams — ``{tenant: weight}`` (``"*"`` = default weight).
        ``None`` disables fair share. Work-conserving: tenants borrow
        idle capacity freely until fleet admission occupancy crosses
        ``fair_share_pressure``, past which a tenant over its weighted
        share is shed (429) so under-share tenants keep finding room.
      fair_share_pressure: occupancy fraction of fleet admission
        capacity (slots + queue depth) past which fair share enforces.
    """

    #: per-front-end ``max_connections=None`` defaults (threads are the
    #: scarce resource one way, sockets the other).
    DEFAULT_MAX_CONNECTIONS = {"threading": 64, "asyncio": 8192}

    def __init__(self, *, server: str = "asyncio",
                 host: str = "127.0.0.1", port: int = 0,
                 max_body_bytes: int = 1 << 20,
                 max_connections: Optional[int] = None,
                 default_max_new_tokens: int = 32,
                 max_new_tokens_cap: Optional[int] = None,
                 default_timeout_s: Optional[float] = None,
                 retry_after_s: float = 1.0,
                 drain_grace_s: float = 30.0,
                 shed_projected_pressure: bool = True,
                 shed_wait_s: float = 5.0,
                 retry_after_max_s: float = 60.0,
                 sse_heartbeat_s: Optional[float] = None,
                 stream_queue_tokens: int = 256,
                 rate_limits: Optional[dict] = None,
                 rate_limit_burst_s: float = 2.0,
                 fair_share_weights: Optional[dict] = None,
                 fair_share_pressure: float = 0.85):
        if server not in self.DEFAULT_MAX_CONNECTIONS:
            raise ValueError(
                f"server must be one of "
                f"{sorted(self.DEFAULT_MAX_CONNECTIONS)} (got {server!r})")
        if max_connections is None:
            max_connections = self.DEFAULT_MAX_CONNECTIONS[server]
        if max_body_bytes < 1 or max_connections < 1:
            raise ValueError("max_body_bytes and max_connections must be >= 1")
        if shed_wait_s <= 0 or retry_after_max_s <= 0:
            raise ValueError("shed_wait_s and retry_after_max_s must be > 0")
        if sse_heartbeat_s is not None and sse_heartbeat_s <= 0:
            raise ValueError("sse_heartbeat_s must be > 0 or None")
        if stream_queue_tokens < 1:
            raise ValueError("stream_queue_tokens must be >= 1")
        self.server = server
        self.host = host
        self.port = int(port)
        self.max_body_bytes = int(max_body_bytes)
        self.max_connections = int(max_connections)
        self.sse_heartbeat_s = (None if sse_heartbeat_s is None
                                else float(sse_heartbeat_s))
        self.stream_queue_tokens = int(stream_queue_tokens)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.max_new_tokens_cap = max_new_tokens_cap
        self.default_timeout_s = default_timeout_s
        self.retry_after_s = float(retry_after_s)
        self.drain_grace_s = float(drain_grace_s)
        self.shed_projected_pressure = bool(shed_projected_pressure)
        self.shed_wait_s = float(shed_wait_s)
        self.retry_after_max_s = float(retry_after_max_s)
        self.rate_limits = None if rate_limits is None else dict(rate_limits)
        self.rate_limit_burst_s = float(rate_limit_burst_s)
        self.fair_share_weights = (None if fair_share_weights is None
                                   else dict(fair_share_weights))
        self.fair_share_pressure = float(fair_share_pressure)


#: request terminal status -> (HTTP code, wire status string)
_STATUS_HTTP = {
    RequestStatus.COMPLETED: (200, "completed"),
    RequestStatus.TIMED_OUT: (408, "timed_out"),
    RequestStatus.CANCELLED: (500, "cancelled"),
    RequestStatus.FAILED: (500, "failed"),
}


#: Curated ``# HELP`` strings for the best-known /metrics families;
#: anything unlisted gets a generic description (promlint only requires
#: that every family HAS one).
_METRIC_HELP = {
    "accelerate_tpu_serving_ttft_ms":
        "Mean time-to-first-token over retired requests (ms).",
    "accelerate_tpu_serving_itl_ms":
        "Mean inter-token latency over decode ticks, device-complete to "
        "device-complete (ms).",
    "accelerate_tpu_serving_host_us_per_tick":
        "Mean host scheduling+commit wall per decode tick (us) — the "
        "non-device share of ITL the async host runtime overlaps.",
    "accelerate_tpu_serving_host_us_per_tick_max":
        "Worst observed host scheduling+commit wall for one tick (us).",
    "accelerate_tpu_serving_emission_stalls":
        "Decode-tick skips of streams whose bounded emission queue was "
        "full (slow on_token consumer flow-controlled).",
    "accelerate_tpu_serving_queue_wait_ms":
        "Mean admission-queue wait over admitted requests (ms).",
    "accelerate_tpu_serving_decode_tokens_per_sec":
        "Committed decode tokens per second of decode-tick wall time.",
    "accelerate_tpu_serving_fleet_failovers":
        "Requests resubmitted to a survivor after their replica died.",
    "accelerate_tpu_serving_fleet_fences":
        "Replicas demoted to FAILED and taken out of rotation.",
    "accelerate_tpu_serving_fleet_restarts":
        "Fenced replicas rebuilt, re-warmed, and returned to rotation.",
    "accelerate_tpu_serving_fleet_hang_fences":
        "Replicas fenced by the supervisor watchdog on heartbeat stall "
        "(engine alive but silent past hang_timeout).",
    "accelerate_tpu_serving_fleet_crash_loops":
        "Replicas parked in CRASH_LOOP by the restart circuit breaker.",
    "accelerate_tpu_serving_replicas_crash_loop":
        "Replicas currently parked in CRASH_LOOP awaiting operator reset.",
    "accelerate_tpu_serving_fleet_page_drain_rate":
        "Observed KV pages freed per second across healthy replicas.",
    "accelerate_tpu_serving_replicas_parked":
        "Replicas currently scaled down to PARKED (engine released, "
        "factory retained for autoscale spawn).",
    "accelerate_tpu_serving_fleet_scale_ups":
        "PARKED replicas rebuilt into rotation by autoscaling.",
    "accelerate_tpu_serving_fleet_scale_downs":
        "Idle replicas drained and parked by autoscaling.",
    "accelerate_tpu_serving_fleet_autoscale_events":
        "Total autoscale actuations (scale-ups plus scale-downs) — the "
        "loop-closure signal.",
    "accelerate_tpu_gateway_pressure_sheds":
        "Completions refused (429) on projected KV-page pressure rather "
        "than queue depth.",
    "accelerate_tpu_gateway_rate_limit_sheds":
        "Completions refused (429) by the per-tenant token-bucket rate "
        "limit; Retry-After derives from the bucket's refill time.",
    "accelerate_tpu_gateway_fair_share_sheds":
        "Completions refused (429) by weighted fair-share admission — "
        "tenant over its share while the fleet is under pressure.",
    "accelerate_tpu_gateway_http_requests":
        "HTTP requests accepted past the connection cap.",
    "accelerate_tpu_gateway_http_inflight":
        "HTTP exchanges currently in flight.",
    "accelerate_tpu_gateway_open_sse_streams":
        "SSE streams currently open (the front end's live concurrency).",
    "accelerate_tpu_gateway_open_sse_streams_max":
        "High-water mark of concurrently open SSE streams.",
    "accelerate_tpu_gateway_conn_rejections":
        "Requests refused (503) at the connection cap — front-end "
        "saturation, distinct from queue-full 429s.",
}


class _BadRequest(ValueError):
    """Client error carrying the 400 payload message."""


def parse_completion(body: dict, cfg: GatewayConfig) -> dict:
    """Validate a ``POST /v1/completions`` JSON body into a submit spec.

    Transport-independent — both the threading handler and the asyncio
    front end funnel through here, so the 400-vs-413 surface cannot
    drift between them. Raises :class:`_BadRequest` with the client-
    facing message on any malformed field."""
    prompt = body.get("prompt")
    if prompt is None:
        raise _BadRequest('missing "prompt" (a list of token ids — '
                          "this gateway serves token ids, not text)")
    try:
        ids = np.asarray(prompt, np.int32)
    except (ValueError, TypeError):
        raise _BadRequest('"prompt" must be a list of token ids '
                          "(optionally nested [[...]])") from None
    if ids.ndim not in (1, 2) or ids.size < 1:
        raise _BadRequest('"prompt" must be a non-empty [S] or [1, S] '
                          "list of token ids")
    max_new = body.get("max_new_tokens", cfg.default_max_new_tokens)
    if not isinstance(max_new, int) or max_new < 1:
        raise _BadRequest('"max_new_tokens" must be a positive integer')
    if (cfg.max_new_tokens_cap is not None
            and max_new > cfg.max_new_tokens_cap):
        raise _BadRequest(
            f'"max_new_tokens" {max_new} exceeds the gateway cap '
            f"({cfg.max_new_tokens_cap})")
    seed = body.get("seed")
    if seed is not None and not isinstance(seed, int):
        raise _BadRequest('"seed" must be an integer')
    timeout = body.get("timeout", cfg.default_timeout_s)
    if timeout is not None and (not isinstance(timeout, (int, float))
                                or timeout <= 0):
        raise _BadRequest('"timeout" must be a positive number')
    adapter = body.get("adapter")
    if adapter is not None and (not isinstance(adapter, str)
                                or not adapter):
        raise _BadRequest('"adapter" must be a non-empty string '
                          "(a registered LoRA adapter name) or omitted")
    priority = body.get("priority")
    if priority is not None and (not isinstance(priority, str)
                                 or not priority):
        raise _BadRequest('"priority" must be a non-empty string '
                          '(a traffic class like "interactive"/"batch") '
                          "or omitted")
    return {
        "prompt_ids": ids,
        "max_new_tokens": max_new,
        "seed": seed,
        "timeout": None if timeout is None else float(timeout),
        "ignore_eos": bool(body.get("ignore_eos", False)),
        "adapter": adapter,
        "priority": priority,
        "stream": bool(body.get("stream", False)),
    }


def clamp_retry_after(cfg: GatewayConfig, seconds: float) -> float:
    """Bound a derived ``Retry-After`` into the gateway's shared
    ``[retry_after_s, retry_after_max_s]`` window. EVERY shed that
    computes its own backoff (pressure drain-rate, rate-limit bucket
    refill) funnels through here — one clamp, both front ends, so no
    response ever advertises an unbounded or sub-floor retry."""
    return min(max(float(seconds), cfg.retry_after_s), cfg.retry_after_max_s)


def tenant_of(spec: dict) -> str:
    """The tenant identity a parsed completion spec bills to: its
    adapter name, or ``"_base"`` for base-model traffic (the underscore
    keeps it out of the valid adapter-name space)."""
    return spec.get("adapter") or "_base"


def summary_payload(fleet, status: str) -> dict:
    """The single summary shape for JSON responses AND the SSE final
    done-event: ``trace_id`` here is what lets a client hand the id
    straight to ``GET /debug/trace``."""
    return {
        "status": status,
        "tokens": [int(t) for t in fleet.tokens],
        "prompt_len": int(fleet.prompt_ids.shape[1]),
        "failovers": fleet.failovers,
        "replica_trail": list(fleet.replica_trail),
        "trace_id": fleet.trace_id,
    }


def completion_result(fleet, retry_after_s: float):
    """Terminal (code, payload, extra_headers) for a FINISHED
    non-streaming completion — including the adapter-bank-full
    residency-pressure 503 special case. Shared by both front ends."""
    if (fleet.status is RequestStatus.FAILED
            and isinstance(fleet.error, AdapterBankFull)):
        # Residency pressure, not a server fault: every bank row was
        # pinned by an in-flight stream at admission time. Structured
        # 503 so clients can back off and retry.
        payload = summary_payload(fleet, "failed")
        payload["error"] = "adapter_bank_full"
        payload["detail"] = str(fleet.error)
        return 503, payload, {"Retry-After": f"{retry_after_s:g}"}
    code, status = _STATUS_HTTP[fleet.status]
    payload = summary_payload(fleet, status)
    if code != 200:
        payload["error"] = (str(fleet.error)
                            if fleet.error is not None else status)
    return code, payload, {}


class ServingGateway:
    """HTTP server over a replica set (or a single engine, auto-wrapped).

    Usage::

        gw = ServingGateway(replica_set, config=GatewayConfig(port=0))
        gw.start()
        ...  # POST to gw.url + "/v1/completions"
        gw.shutdown(drain=True)

    Also a context manager (``start`` on enter, drain-shutdown on exit).
    """

    def __init__(self, replicas, *, config: Optional[GatewayConfig] = None,
                 stats: Optional[GatewayStats] = None, accelerator=None):
        if isinstance(replicas, ServingEngine):
            replicas = ReplicaSet([replicas])
        if not isinstance(replicas, ReplicaSet):
            raise TypeError(
                f"replicas must be a ReplicaSet or ServingEngine "
                f"(got {type(replicas).__name__})")
        self.replica_set = replicas
        self.config = config if config is not None else GatewayConfig()
        if stats is None and accelerator is not None:
            stats = getattr(accelerator, "gateway_stats", None)
        self.stats = stats if stats is not None else GatewayStats()
        # Tenant policy (control plane): built once from config; both
        # front ends consult them through submit_or_error only.
        from .control import FairShareAdmission, TenantRateLimiter

        self.rate_limiter = None
        if self.config.rate_limits:
            self.rate_limiter = TenantRateLimiter(
                self.config.rate_limits,
                burst_s=self.config.rate_limit_burst_s)
        self.fair_share = None
        if self.config.fair_share_weights is not None:
            self.fair_share = FairShareAdmission(
                self.config.fair_share_weights,
                pressure=self.config.fair_share_pressure)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._draining = False
        self._shutdown_lock = threading.Lock()
        self._conn_slots = threading.BoundedSemaphore(
            self.config.max_connections)
        # One process-wide compile accounting for /metrics. jax.monitoring
        # events are process-global, so the GATEWAY owns the single
        # watcher — summing per-engine watchers would count every compile
        # once per replica. Registered in start(), not here, so a gateway
        # that is constructed but never served leaks no listeners.
        self.compile_watcher = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        """Bind and serve in a daemon thread (idempotent). With
        ``config.port == 0`` the OS picks the port; read it back from
        :attr:`port` / :attr:`url`. Which front end binds is
        ``config.server``; either way :attr:`_server` duck-types the
        ``shutdown()`` / ``server_close()`` / ``server_address`` surface
        the lifecycle methods drive."""
        if self._server is not None:
            return
        if self.compile_watcher is None:
            from ..utils.profiling import CompileWatcher

            self.compile_watcher = CompileWatcher().start()
        if self.config.server == "asyncio":
            from .gateway_aio import AsyncioGatewayServer

            self._server = AsyncioGatewayServer(self)
            self._thread = self._server.thread
            return
        handler = type("GatewayHandler", (_Handler,), {"gateway": self})
        self._server = ThreadingHTTPServer(
            (self.config.host, self.config.port), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="serving-gateway",
            daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        if self._server is None:
            raise RuntimeError("gateway not started")
        return self._server.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.config.host}:{self.port}"

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def ready(self) -> bool:
        """The ``/readyz`` condition: accepting AND >= 1 healthy replica."""
        return not self._draining and self.replica_set.ready

    def drain(self):
        """Stop taking new work (readyz 503, completions 503); in-flight
        streams keep running. ``shutdown`` completes the exit."""
        self._draining = True
        self.replica_set.drain()

    def shutdown(self, drain: bool = True, timeout: Optional[float] = None):
        """Graceful exit: drain, wait (bounded by ``drain_grace_s``) for
        in-flight HTTP exchanges, stop the listener, shut the replicas
        down (which also flushes pending async checkpoint saves).
        ``drain=False`` skips the waiting and cancels in-flight work."""
        with self._shutdown_lock:
            self._draining = True
            if drain:
                self.replica_set.drain()
                deadline = time.monotonic() + self.config.drain_grace_s
                while (self.stats.summary()["http_inflight"] > 0
                        and time.monotonic() < deadline):
                    time.sleep(0.01)
            if self._server is not None:
                self._server.shutdown()
                self._server.server_close()
                self._server = None
                self._thread = None
            if self.compile_watcher is not None:
                self.compile_watcher.stop()
            self.replica_set.shutdown(drain=drain, timeout=timeout)

    def install_signal_handlers(self, signals=(signal.SIGTERM,
                                               signal.SIGINT)) -> bool:
        """Wire graceful drain to process signals (SIGTERM is what both
        k8s and TPU preemption notices deliver). Returns False — without
        installing — when not on the main thread, where CPython forbids
        ``signal.signal``."""
        if threading.current_thread() is not threading.main_thread():
            return False

        def _handle(signum, frame):
            # The handler must not block: drain flips flags, the real
            # shutdown runs on its own thread.
            threading.Thread(target=self.shutdown, kwargs={"drain": True},
                             name="gateway-drain", daemon=True).start()

        for s in signals:
            signal.signal(s, _handle)
        return True

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown(drain=exc[0] is None)

    # -- admission (shared by both front ends) ----------------------------
    def pressure_retry_after(self, spec: dict) -> Optional[float]:
        """Projected-pressure shed decision: a ``Retry-After`` in seconds
        when this completion should be 429'd, else None (admit).

        Sheds only when (a) the fleet's least-loaded paged pool cannot
        cover this request's worst-case page demand on top of what is
        already admitted + queued, AND (b) pages have been *observed*
        draining but too slowly to clear that deficit within
        ``shed_wait_s``. Rule (b) means a cold fleet (nothing freed yet)
        or a dense fleet never sheds — queue-depth 429s and deadline
        408s keep covering those.
        """
        cfg = self.config
        if not cfg.shed_projected_pressure:
            return None
        rs = self.replica_set
        total = int(spec["prompt_ids"].shape[-1]) + int(spec["max_new_tokens"])
        deficit = rs.projected_page_deficit(total)
        if deficit <= 0:
            return None
        rate = rs.page_drain_rate()
        if rate <= 0 or deficit <= rate * cfg.shed_wait_s:
            return None
        return clamp_retry_after(cfg, deficit / rate)

    def submit_or_error(self, spec: dict, trace_id: str, on_token=None):
        """Admit one parsed completion spec: ``(fleet, None)`` on success,
        ``(None, (code, payload, extra_headers))`` on any refusal —
        rate-limit 429, fair-share 429, projected-pressure 429,
        queue-full 429, unknown-adapter 404, no-healthy-replica 503, or
        invalid-parameter 400. The single admission path both front ends
        share, so backpressure semantics cannot drift between them.

        Tenant policy runs first, cheapest-check-first: the token-bucket
        rate limit (pure arithmetic, its Retry-After is the bucket's own
        refill time clamped through :func:`clamp_retry_after` like every
        other shed), then weighted fair share (a successful acquire is
        released exactly once via the fleet request's done callback —
        including failure/cancel terminals), then the fleet-pressure and
        submit paths exactly as before."""
        cfg = self.config
        retry_headers = {"Retry-After": f"{cfg.retry_after_s:g}"}
        tenant = tenant_of(spec)
        if self.rate_limiter is not None:
            refill_in = self.rate_limiter.admit(tenant)
            if refill_in is not None:
                self.stats.record_rate_limit_shed()
                return None, (
                    429, {"error": "rate_limited",
                          "detail": f"tenant {tenant!r} is over its "
                                    "request rate; retry later",
                          "tenant": tenant},
                    {"Retry-After":
                     f"{clamp_retry_after(cfg, refill_in):g}"})
        acquired = False
        if self.fair_share is not None:
            capacity = self.replica_set.admission_capacity()
            if not self.fair_share.try_acquire(tenant, capacity):
                self.stats.record_fair_share_shed()
                return None, (
                    429, {"error": "fair_share_exceeded",
                          "detail": f"tenant {tenant!r} is over its "
                                    "weighted share of in-flight streams "
                                    "under fleet pressure; retry later",
                          "tenant": tenant},
                    retry_headers)
            acquired = True

        def _refuse(resp):
            # Any refusal past a successful fair-share acquire returns
            # the tenant's in-flight slot — no leaked shares.
            if acquired:
                self.fair_share.release(tenant)
            return None, resp

        retry_in = self.pressure_retry_after(spec)
        if retry_in is not None:
            self.stats.record_pressure_shed()
            return _refuse((
                429, {"error": "projected KV page pressure: admitted and "
                               "queued work exceeds pool headroom; "
                               "retry later"},
                {"Retry-After": f"{retry_in:g}"}))
        try:
            fleet = self.replica_set.submit(
                spec["prompt_ids"],
                max_new_tokens=spec["max_new_tokens"],
                seed=spec["seed"], timeout=spec["timeout"],
                ignore_eos=spec["ignore_eos"],
                adapter=spec["adapter"],
                priority=spec.get("priority"),
                trace_id=trace_id,
                on_token=on_token)
        except QueueFull:
            return _refuse((429, {"error": "all replicas saturated; "
                                           "retry later"}, retry_headers))
        except LookupError as e:
            return _refuse((404, {"error": "unknown_adapter",
                                  "detail": str(e)}, {}))
        except RuntimeError as e:
            return _refuse((503, {"error": f"no healthy replica: {e}"},
                            retry_headers))
        except ValueError as e:
            return _refuse((400, {"error": str(e)}, {}))
        except BaseException:
            if acquired:
                self.fair_share.release(tenant)
            raise
        if acquired:
            fleet.add_done_callback(
                lambda _f, fs=self.fair_share, t=tenant: fs.release(t))
        return fleet, None

    # -- metrics ----------------------------------------------------------
    def metrics_text(self) -> str:
        """The ``/metrics`` body: Prometheus text exposition (version
        0.0.4) of fleet-merged engine counters (gauges PLUS real
        cumulative-bucket latency histograms), router health/failover
        counters, process-wide XLA compile counters, and the gateway's
        HTTP counters. Every family carries ``# HELP``/``# TYPE`` —
        ``observability.promlint`` keeps this scrape-clean in tests."""
        lines = []

        def emit(name, value, mtype="gauge", help_=None):
            lines.append(f"# HELP {name} "
                         + (help_ or _METRIC_HELP.get(
                             name, f"accelerate-tpu serving-stack {mtype}.")))
            lines.append(f"# TYPE {name} {mtype}")
            v = float(value)
            lines.append(f"{name} {int(v) if v == int(v) else v}")

        merged = self.replica_set.merged_stats()
        for k, v in self.replica_set.fleet_metrics().items():
            if k.startswith(("adapter/", "priority/")):
                continue  # re-emitted below as properly labeled series
            emit(f"accelerate_tpu_serving_{k}", v)
        # Latency distributions: the *_ms summary gauges above keep their
        # names; the histogram twin gets a _hist-suffixed family so the
        # two never collide in one exposition.
        for hname, snap in sorted(merged.histograms().items()):
            fam = f"accelerate_tpu_serving_{hname}_hist"
            lines.append(f"# HELP {fam} Fleet-wide distribution of "
                         f"{hname} (cumulative buckets, ms).")
            lines.append(f"# TYPE {fam} histogram")
            for bound, cum in snap["cumulative"]:
                le = "+Inf" if bound == "+Inf" else str(float(bound))
                lines.append(f'{fam}_bucket{{le="{le}"}} {cum}')
            s = float(snap["sum"])
            lines.append(f"{fam}_sum {int(s) if s == int(s) else s}")
            lines.append(f"{fam}_count {snap['count']}")
        per_adapter = merged.per_adapter()
        if per_adapter:
            counters = sorted(next(iter(per_adapter.values())))
            for c in counters:
                lines.append(
                    f"# HELP accelerate_tpu_serving_adapter_{c} "
                    f"Per-adapter {c} across the fleet.")
                lines.append(
                    f"# TYPE accelerate_tpu_serving_adapter_{c} counter")
                for name in sorted(per_adapter):
                    lines.append(
                        f'accelerate_tpu_serving_adapter_{c}'
                        f'{{adapter="{name}"}} {per_adapter[name][c]}')
        per_priority = merged.per_priority()
        if per_priority:
            counters = sorted(next(iter(per_priority.values())))
            for c in counters:
                lines.append(
                    f"# HELP accelerate_tpu_serving_priority_{c} "
                    f"Per-priority (traffic class) {c} across the fleet — "
                    "the class each engine's priority policy schedules "
                    "and preempts by.")
                lines.append(
                    f"# TYPE accelerate_tpu_serving_priority_{c} counter")
                for name in sorted(per_priority):
                    lines.append(
                        f'accelerate_tpu_serving_priority_{c}'
                        f'{{priority="{name}"}} {per_priority[name][c]}')
        if self.compile_watcher is not None:
            cs = self.compile_watcher.summary()
            emit("accelerate_tpu_xla_compile_events_total",
                 cs["compile_events"], "counter",
                 help_="XLA compile/trace events observed in-process since "
                       "the gateway started (0 growth = zero-recompile "
                       "steady state).")
            emit("accelerate_tpu_xla_compile_seconds_total",
                 cs["compile_secs"], "counter",
                 help_="Wall seconds spent in observed XLA compiles.")
            emit("accelerate_tpu_xla_compilation_cache_hits_total",
                 cs["compilation_cache_hits"], "counter",
                 help_="XLA compilation-cache hit events observed "
                       "in-process.")
        for k, v in self.stats.summary().items():
            emit(f"accelerate_tpu_gateway_{k}", v)
        lines.append(
            "# HELP accelerate_tpu_gateway_responses_total "
            "HTTP responses by route and status code.")
        lines.append(
            "# TYPE accelerate_tpu_gateway_responses_total counter")
        for (route, code), n in sorted(self.stats.by_route().items()):
            lines.append(
                'accelerate_tpu_gateway_responses_total'
                f'{{route="{route}",code="{code}"}} {n}')
        return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    """Per-connection handler; ``gateway`` is injected as a class
    attribute by ``ServingGateway.start``."""

    gateway: ServingGateway = None  # type: ignore[assignment]
    protocol_version = "HTTP/1.1"
    # Quieten the default per-request stderr lines; errors still surface
    # through status codes and /metrics.
    def log_message(self, fmt, *args):  # noqa: D102
        pass

    # -- plumbing ---------------------------------------------------------
    def _send_json(self, code: int, payload: dict, route: str,
                   extra_headers: Optional[dict] = None,
                   body_bytes_in: int = 0,
                   trace_id: Optional[str] = None):
        if trace_id is not None:
            # Correlation id rides both channels: the JSON body (clients
            # that log payloads) and the X-Request-Id header (proxies).
            payload.setdefault("trace_id", trace_id)
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if trace_id is not None:
            self.send_header("X-Request-Id", trace_id)
        for k, v in (extra_headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)
        self.gateway.stats.record_response(route, code,
                                           body_bytes=body_bytes_in)

    def _send_text(self, code: int, text: str, route: str,
                   content_type: str = "text/plain; charset=utf-8",
                   extra_headers: Optional[dict] = None):
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)
        self.gateway.stats.record_response(route, code)

    def _retry_after(self) -> dict:
        return {"Retry-After": f"{self.gateway.config.retry_after_s:g}"}

    # -- GET --------------------------------------------------------------
    def do_GET(self):  # noqa: N802 (http.server naming)
        gw = self.gateway
        parsed = urlparse(self.path)
        path = parsed.path
        if not self._conn_enter(path):
            return
        try:
            if path == "/healthz":
                self._send_text(200, "ok\n", "/healthz")
            elif path == "/readyz":
                if gw.ready:
                    self._send_text(200, "ready\n", "/readyz")
                else:
                    if gw.draining:
                        body = "draining\n"
                    else:
                        fm = gw.replica_set.fleet_metrics()
                        looped = int(fm.get("replicas_crash_loop", 0))
                        body = ("no healthy replica"
                                + (f" ({looped} crash-looped)" if looped
                                   else "") + "\n")
                    self._send_text(503, body, "/readyz",
                                    extra_headers=self._retry_after())
            elif path == "/metrics":
                self._send_text(200, gw.metrics_text(), "/metrics",
                                content_type="text/plain; version=0.0.4; "
                                             "charset=utf-8")
            elif path == "/debug/trace":
                self._debug_trace(parse_qs(parsed.query))
            else:
                self._send_json(404, {"error": "not found"}, path)
        finally:
            self._conn_exit()

    def _debug_trace(self, query: dict):
        """``GET /debug/trace`` — the whole fleet's buffered spans as one
        Chrome-trace JSON; ``?id=<trace_id>`` narrows to one request's
        timeline (404 when no replica buffered a span for that id)."""
        route = "/debug/trace"
        raw = (query.get("id") or [None])[0]
        tid = None
        if raw is not None:
            tid = clean_trace_id(raw)
            if tid is None:
                self._send_json(400, {"error": "invalid trace id"}, route)
                return
        trace = self.gateway.replica_set.chrome_trace(tid)
        if tid is not None and not any(
                ev.get("ph") != "M" for ev in trace["traceEvents"]):
            self._send_json(404, {"error": "trace not found",
                                  "trace_id": tid}, route)
            return
        self._send_text(200, json.dumps(trace), route,
                        content_type="application/json")

    # -- POST -------------------------------------------------------------
    def do_POST(self):  # noqa: N802
        gw = self.gateway
        if self.path != "/v1/completions":
            self._send_json(404, {"error": "not found"}, self.path)
            return
        route = "/v1/completions"
        if not self._conn_enter(route):
            return
        # Minted before anything can fail so even a 4xx/5xx body carries
        # a correlation id (the client's own X-Request-Id when it sent a
        # well-formed one).
        trace_id = (clean_trace_id(self.headers.get("X-Request-Id"))
                    or new_trace_id())
        try:
            if gw.draining:
                self._send_json(503, {"error": "gateway draining"}, route,
                                extra_headers=self._retry_after(),
                                trace_id=trace_id)
                return
            try:
                body, nbytes = self._read_body()
                spec = self._parse_completion(body)
            except _BadRequest as e:
                code = 413 if "max_body_bytes" in str(e) else 400
                self._send_json(code, {"error": str(e)}, route,
                                trace_id=trace_id)
                return
            self._run_completion(spec, route, nbytes, trace_id)
        finally:
            self._conn_exit()

    def _read_body(self) -> tuple[dict, int]:
        cfg = self.gateway.config
        try:
            length = int(self.headers.get("Content-Length", ""))
        except ValueError:
            raise _BadRequest("Content-Length required") from None
        if length > cfg.max_body_bytes:
            raise _BadRequest(
                f"request body {length} bytes exceeds max_body_bytes "
                f"({cfg.max_body_bytes})")
        raw = self.rfile.read(length)
        try:
            body = json.loads(raw)
        except json.JSONDecodeError as e:
            raise _BadRequest(f"invalid JSON: {e}") from None
        if not isinstance(body, dict):
            raise _BadRequest("request body must be a JSON object")
        return body, length

    def _parse_completion(self, body: dict) -> dict:
        return parse_completion(body, self.gateway.config)

    def _run_completion(self, spec: dict, route: str, nbytes: int,
                        trace_id: str):
        gw = self.gateway
        stream = spec.pop("stream")
        token_q: Optional[queue.Queue] = queue.Queue() if stream else None
        fleet, err = gw.submit_or_error(
            spec, trace_id, on_token=token_q.put if stream else None)
        if err is not None:
            code, payload, headers = err
            self._send_json(code, payload, route, extra_headers=headers,
                            body_bytes_in=nbytes, trace_id=trace_id)
            return
        if stream:
            self._stream_sse(fleet, token_q, route, nbytes)
        else:
            fleet.wait()  # bounded by the per-request deadline when set
            code, payload, headers = completion_result(
                fleet, gw.config.retry_after_s)
            self._send_json(code, payload, route, extra_headers=headers,
                            body_bytes_in=nbytes, trace_id=trace_id)

    def _stream_sse(self, fleet, token_q: queue.Queue, route: str,
                    nbytes: int):
        """One SSE event per token as the engine commits it; a final
        summary event carries the terminal status (and failover count) so
        clients can tell a complete stream from a truncated one. A broken
        client socket cancels the request — its slot frees at the next
        scheduler pass instead of decoding into the void. With
        ``sse_heartbeat_s`` set, a ``: ping`` comment frame goes out on
        any stream idle past it (deep PREFILLING backlogs) so
        intermediaries don't sever long-queued streams."""
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.send_header("X-Request-Id", fleet.trace_id)
        self.end_headers()
        self.close_connection = True
        heartbeat = self.gateway.config.sse_heartbeat_s
        last_write = time.monotonic()
        sent = 0
        self.gateway.stats.stream_enter()
        try:
            while True:
                try:
                    tok = token_q.get(timeout=0.05)
                except queue.Empty:
                    if fleet.done and token_q.empty():
                        break
                    if (heartbeat is not None
                            and time.monotonic() - last_write >= heartbeat):
                        self.wfile.write(b": ping\n\n")
                        self.wfile.flush()
                        last_write = time.monotonic()
                    continue
                self.wfile.write(
                    f"data: {json.dumps({'token': int(tok)})}\n\n".encode())
                self.wfile.flush()
                last_write = time.monotonic()
                sent += 1
            code, status = _STATUS_HTTP[fleet.status]
            final = summary_payload(fleet, status)
            final["done"] = True
            if fleet.status is not RequestStatus.COMPLETED:
                final["error"] = (str(fleet.error)
                                  if fleet.error is not None else status)
            self.wfile.write(f"data: {json.dumps(final)}\n\n".encode())
            self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError):
            fleet.cancel()
            code = 499  # client closed; nothing more can be written
        finally:
            self.gateway.stats.stream_exit()
        self.gateway.stats.record_response(route, code, body_bytes=nbytes)
        self.gateway.stats.record_stream(sent)

    # -- connection cap ----------------------------------------------------
    def _conn_enter(self, route: str) -> bool:
        """Take an in-flight slot; refuse with 503 when the cap is hit
        (without blocking — the admission queues are the real wait)."""
        if not self.gateway._conn_slots.acquire(blocking=False):
            self.gateway.stats.record_conn_rejection()
            try:
                self._send_json(503, {"error": "connection limit reached"},
                                route, extra_headers=self._retry_after())
            except (BrokenPipeError, ConnectionResetError):
                pass
            return False
        self.gateway.stats.inflight_enter()
        return True

    def _conn_exit(self):
        self.gateway.stats.inflight_exit()
        self.gateway._conn_slots.release()
