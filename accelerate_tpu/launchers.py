"""In-process launchers (reference: src/accelerate/launchers.py —
notebook_launcher :40, debug_launcher :269).

The reference forks one process per device (`xmp.spawn` on TPU :135-150,
elastic on GPU :231-245) because torch needs a process per accelerator.
JAX drives every local chip from ONE process, so "launching" from a
notebook is environment setup, not forking — which also sidesteps the
reference's fork-after-CUDA-init failure modes (launchers.py:177-186).
Multi-host notebooks (one kernel per host) pass coordinator details.
"""

from __future__ import annotations

import os
from typing import Optional


def notebook_launcher(
    function,
    args=(),
    num_processes: Optional[int] = None,
    mixed_precision: str = "no",
    use_port: str = "29500",
    master_addr: Optional[str] = None,
    node_rank: int = 0,
    num_nodes: int = 1,
    debug: bool = False,
    **mesh_axes: int,
):
    """Run ``function(*args)`` configured for this host's devices.

    ``num_processes`` is accepted for API parity; on JAX it must equal the
    host count (devices are not processes). ``mesh_axes`` (dp/fsdp/tp/cp/
    ep/pp) seed the mesh env exactly like `accelerate-tpu launch` flags.
    """
    from .utils.environment import env_var, patch_environment

    env: dict[str, str] = {}
    if num_nodes > 1:
        if master_addr is None:
            raise ValueError("multi-node notebook_launcher needs master_addr")
        env[env_var("COORDINATOR_ADDRESS")] = f"{master_addr}:{use_port}"
        env[env_var("NUM_PROCESSES")] = str(num_nodes)
        env[env_var("PROCESS_ID")] = str(node_rank)
    if mixed_precision != "no":
        env[env_var("MIXED_PRECISION")] = mixed_precision
    if debug:
        env[env_var("DEBUG")] = "true"
    for ax, size in mesh_axes.items():
        if ax in ("dp", "fsdp", "tp", "cp", "ep", "pp"):
            env[env_var(f"MESH_{ax.upper()}")] = str(size)
    env[env_var("FORK_LAUNCHED")] = "false"
    try:
        with patch_environment(**env):
            return function(*args)
    finally:
        from .state import AcceleratorState, GradientState, PartialState

        AcceleratorState._reset_state()
        GradientState._reset_state()
        PartialState._reset_state()


def debug_launcher(function, args=(), num_processes: int = 8):
    """Run ``function`` on N emulated CPU devices (reference: debug_launcher
    :269 forks CPU workers with a file-store rendezvous; here emulation is
    in-process via the host-platform device count)."""
    from .test_utils import use_emulated_devices

    use_emulated_devices(num_processes)
    return notebook_launcher(function, args)
