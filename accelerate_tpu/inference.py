"""Stage-parallel (pipelined) inference — PiPPy capability parity.

Reference: inference.py (185 LoC) — ``prepare_pippy`` traces the torch
model, splits it at device-map boundaries, wraps it in
``torch.distributed.pipelining``'s ``ScheduleGPipe`` (reference:
inference.py:73-96) and pads microbatches so uneven batch sizes work
(reference: inference.py:99-121).

Here the heavy machinery already exists: a pipelined model (stacked layers
sharded over ``pp``; see parallel/pipeline.py) *is* the split+schedule, and
jit compiles it once for all stages. What this module adds is the
user-facing wrapper:

* microbatch padding — arbitrary batch sizes get edge-padded up to a
  multiple of the microbatch count and sliced back after the forward;
* a jitted, eval-mode forward with the model's precision policy applied;
* conversion from a sequential checkpoint layout when needed.
"""

from __future__ import annotations

import contextlib
from typing import Callable, Optional

import jax
import jax.numpy as jnp


def pad_batch_to_multiple(args, multiple: int):
    """Edge-pad the leading (batch) dim of every array leaf up to a multiple.

    Returns ``(padded_args, original_batch)``. Mirrors the reference's
    microbatch padding (reference: inference.py:99-121) — padding rows repeat
    the last example, so shapes stay static and the padded rows are sliced
    off after the forward.
    """
    leaves = [l for l in jax.tree_util.tree_leaves(args) if hasattr(l, "shape") and l.ndim > 0]
    if not leaves:
        return args, None
    batch = leaves[0].shape[0]
    rem = batch % multiple
    if rem == 0:
        return args, batch
    pad = multiple - rem

    def _pad(leaf):
        if not hasattr(leaf, "shape") or leaf.ndim == 0 or leaf.shape[0] != batch:
            return leaf
        edge = jnp.repeat(leaf[-1:], pad, axis=0)
        return jnp.concatenate([leaf, edge], axis=0)

    return jax.tree_util.tree_map(_pad, args), batch


class PipelinedInferencer:
    """Callable wrapper: padded, jitted, stage-parallel forward."""

    def __init__(self, apply_fn: Callable, params, num_microbatches: int, policy=None, mesh=None):
        self.params = params
        self.num_microbatches = int(num_microbatches)
        self.mesh = mesh
        self.policy = policy

        def fwd(params, args, kwargs):
            p = policy.cast_to_compute(params) if policy is not None else params
            out = apply_fn(p, *args, **kwargs)
            return policy.cast_to_output(out) if policy is not None else out

        self._jit_fwd = jax.jit(fwd)

    def __call__(self, *args, **kwargs):
        # Pad args and kwargs as ONE pytree so batch-dim arrays passed by
        # keyword (attention masks, positions) stay aligned with the inputs.
        (args, kwargs), batch = pad_batch_to_multiple((args, kwargs), self.num_microbatches)
        ctx = self.mesh if self.mesh is not None else contextlib.nullcontext()
        with ctx:
            out = self._jit_fwd(self.params, args, kwargs)
        if batch is None:
            return out
        padded_batch = batch + (-batch) % self.num_microbatches
        if padded_batch == batch:
            return out
        return jax.tree_util.tree_map(
            lambda l: l[:batch]
            if hasattr(l, "shape") and l.ndim > 0 and l.shape[0] == padded_batch
            else l,
            out,
        )


def resolve_model_source(model, params=None, accelerator=None):
    """Resolve ``(module, apply_fn, params, mesh, policy)`` from any model
    spelling the library accepts — an accelerate_tpu ``Model`` /
    ``AcceleratedModel`` (wrapped flax module + params, possibly carrying a
    mesh and precision policy), a bare flax module (``.apply`` over a
    variables dict), or a raw ``apply_fn(params, *args)`` callable.

    Shared by :func:`prepare_pipeline` and the serving engine so both
    unwrap prepared models identically. ``module`` is the underlying flax
    module when one is recoverable (needed by cache-threading consumers),
    else None; ``params`` may come back None when neither the caller nor
    the model supplies them — callers decide whether that is an error.
    """
    module = getattr(model, "module", None)
    if hasattr(model, "apply_fn"):  # accelerate_tpu Model / AcceleratedModel
        apply_fn = model.apply_fn
        params = params if params is not None else model.params
    elif hasattr(model, "apply"):
        module = model
        raw_apply = model.apply

        def apply_fn(p, *args, **kwargs):
            variables = p if isinstance(p, dict) and "params" in p else {"params": p}
            return raw_apply(variables, *args, **kwargs)

    elif callable(model):
        apply_fn = model
    else:
        raise TypeError(f"cannot resolve a model from {type(model)}")
    policy = accelerator.policy if accelerator is not None else getattr(model, "policy", None)
    mesh = accelerator.mesh if accelerator is not None else getattr(model, "mesh", None)
    return module, apply_fn, params, mesh, policy


def prepare_pipeline(
    model,
    params=None,
    accelerator=None,
    num_microbatches: Optional[int] = None,
):
    """Build a stage-parallel inference callable (reference: prepare_pippy,
    inference.py:124).

    ``model`` is a pipelined model object (``.apply`` over stacked layers —
    e.g. `models.llama.PipelinedLlamaForCausalLM`) or any
    ``apply_fn(params, *args)``. Params default to ``model.params`` /
    the prepared model's; the mesh and precision policy come from
    ``accelerator`` when given. The returned callable accepts ANY batch size:
    inputs are edge-padded to a multiple of the microbatch count and outputs
    sliced back.
    """
    _, apply_fn, params, mesh, policy = resolve_model_source(
        model, params=params, accelerator=accelerator)
    if params is None:
        raise ValueError("prepare_pipeline needs params (pass params= or a prepared Model)")
    if num_microbatches is None:
        # Match what the pipeline will actually use: the model's own count,
        # then the accelerator's pp plugin, then the pp axis size (the
        # pipeline_apply default when num_microbatches is unset).
        num_microbatches = getattr(model, "num_microbatches", None)
        if num_microbatches is None and accelerator is not None:
            pp_plugin = accelerator.state.pp_plugin
            if pp_plugin is not None and pp_plugin.num_microbatches > 1:
                num_microbatches = pp_plugin.num_microbatches
        if num_microbatches is None and mesh is not None:
            num_microbatches = max(dict(mesh.shape).get("pp", 1), 1)
        if num_microbatches is None:
            num_microbatches = 1
    return PipelinedInferencer(apply_fn, params, num_microbatches, policy=policy, mesh=mesh)


#: Reference-parity alias (reference: inference.py:124 ``prepare_pippy``) —
#: the stage-parallel inference builder under the name migrating scripts use.
prepare_pippy = prepare_pipeline
