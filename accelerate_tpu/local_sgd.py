"""Local SGD: data-parallel training with infrequent parameter averaging.

Reference: local_sgd.py:19-103 — wraps a torch loop, calls
``model.no_sync()`` to skip DDP's per-step gradient all-reduce and every
``local_sgd_steps`` averages parameters with ``reduce(mean)``. The win is
communication *frequency*: one collective per N steps instead of per step,
which matters when the interconnect is slow relative to compute (multi-slice
DCN, preemptible pods).

TPU-native design — divergent replicas as a batch dimension:

Under GSPMD, replicated parameters are definitionally identical on every dp
shard, so "skip the sync" cannot be expressed by omitting a collective the
way DDP's ``no_sync`` does. Instead the replicas are made *explicit*: every
param/opt-state leaf gains a leading ``[dp, ...]`` dim sharded over the
``dp`` mesh axis, the per-shard optimizer step runs under ``vmap`` over that
dim (pure local compute — each device updates its own replica, zero
communication), and the periodic average is one ``mean`` over the stacked
dim (a single all-reduce, the only collective in the whole scheme). Both
phases are ordinary jitted GSPMD programs, so Local SGD composes with the
rest of the framework instead of needing a DDP-style comm hook.

Usage (API shape mirrors the reference)::

    with LocalSGD(accelerator, model, optimizer, loss_fn,
                  local_sgd_steps=8) as lsgd:
        for batch in dl:
            metrics = lsgd.step(batch)   # per-shard local update
    # exiting averages replicas once more and writes back to `model`
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp


class LocalSGD:
    """Context manager running per-dp-shard local steps with periodic
    parameter averaging (reference: local_sgd.py:19)."""

    def __init__(
        self,
        accelerator,
        model,
        optimizer,
        loss_fn: Callable,
        local_sgd_steps: int = 8,
        enabled: bool = True,
        max_grad_norm: Optional[float] = None,
    ):
        if accelerator.state.mixed_precision == "fp16":
            raise ValueError(
                "LocalSGD does not support fp16 loss scaling; use bf16 "
                "(the TPU-native precision) instead."
            )
        self.accelerator = accelerator
        self.model = model
        self.optimizer = optimizer
        self.loss_fn = loss_fn
        self.local_sgd_steps = int(local_sgd_steps)
        mesh = accelerator.mesh
        self.dp = int(dict(mesh.shape).get("dp", 1)) if mesh is not None else 1
        self.enabled = bool(enabled) and self.dp > 1
        self.max_grad_norm = max_grad_norm
        self._step_count = 0
        self._stacked_params = None
        self._stacked_opt = None
        self._local_step_jit = None
        self._average_jit = None
        self._fallback_step = None

    # ------------------------------------------------------------------

    def __enter__(self):
        if not self.enabled:
            # Degenerate (dp==1 or disabled): plain fused train step
            # (reference: enabled=False is a no-op wrapper, local_sgd.py:55).
            self._fallback_step = self.accelerator.compile_train_step(
                self.loss_fn, model=self.model, optimizer=self.optimizer,
                max_grad_norm=self.max_grad_norm, donate=False,
            )
            return self

        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.accelerator.mesh
        dp = self.dp
        policy = self.accelerator.policy
        tx = self.optimizer.tx
        loss_fn = self.loss_fn
        accepts_rng = self.accelerator._loss_fn_accepts_rng(loss_fn)
        max_grad_norm = self.max_grad_norm

        def _stack_spec(leaf_sharding):
            spec = tuple(leaf_sharding.spec) if hasattr(leaf_sharding, "spec") else ()
            return NamedSharding(mesh, P("dp", *spec))

        param_shardings = self.model.param_shardings
        stacked_shardings = jax.tree_util.tree_map(
            _stack_spec, param_shardings,
            is_leaf=lambda x: hasattr(x, "spec"),
        )

        def _stack(params):
            return jax.tree_util.tree_map(
                lambda p, s: jax.device_put(jnp.broadcast_to(p[None], (dp,) + p.shape), s),
                params, stacked_shardings,
            )

        self._stacked_params = _stack(self.model.params)
        if self.optimizer.opt_state is not None:
            # Preserve accumulated optimizer state (Adam moments etc.) —
            # replicate it into each shard's replica.
            self._stacked_opt = jax.jit(
                lambda o: jax.tree_util.tree_map(
                    lambda l: jnp.broadcast_to(l[None], (dp,) + jnp.shape(l)), o
                )
            )(self.optimizer.opt_state)
        else:
            self._stacked_opt = jax.jit(jax.vmap(tx.init))(self._stacked_params)

        def per_shard_update(params, batch, rng):
            def compute(p):
                cp = policy.cast_to_compute(p)
                out = loss_fn(cp, batch, rng) if accepts_rng else loss_fn(cp, batch)
                loss = out[0] if isinstance(out, tuple) else out
                return loss.astype(jnp.float32)

            return jax.value_and_grad(compute)(params)

        def local_step(stacked_params, stacked_opt, batch, rng):
            import optax

            rngs = jax.random.split(rng, dp)
            losses, grads = jax.vmap(per_shard_update, in_axes=(0, 0, 0))(
                stacked_params, batch, rngs
            )
            if max_grad_norm is not None:
                def clip_one(g):
                    leaves = jax.tree_util.tree_leaves(g)
                    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves))
                    factor = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                    return jax.tree_util.tree_map(lambda l: (l * factor).astype(l.dtype), g)

                grads = jax.vmap(clip_one)(grads)

            def update_one(g, o, p):
                updates, new_o = tx.update(g, o, p)
                return optax.apply_updates(p, updates), new_o

            new_params, new_opt = jax.vmap(update_one)(grads, stacked_opt, stacked_params)
            return new_params, new_opt, losses.mean()

        def average(stacked_params):
            return jax.tree_util.tree_map(
                lambda p: jnp.broadcast_to(jnp.mean(p, axis=0, keepdims=True), p.shape),
                stacked_params,
            )

        self._local_step_jit = jax.jit(local_step, donate_argnums=(0, 1))
        self._average_jit = jax.jit(average, donate_argnums=(0,), out_shardings=stacked_shardings)
        return self

    def step(self, batch):
        """One local training step. ``batch`` leaves are ``[global_batch, ...]``
        (split evenly across dp shards) and must have
        ``global_batch % dp == 0``."""
        if not self.enabled:
            return self._fallback_step(batch)

        dp = self.dp

        def to_sharded(leaf):
            leaf = jnp.asarray(leaf)
            if leaf.shape[0] % dp != 0:
                raise ValueError(
                    f"batch dim {leaf.shape[0]} not divisible by dp={dp}"
                )
            return leaf.reshape((dp, leaf.shape[0] // dp) + leaf.shape[1:])

        batch = jax.tree_util.tree_map(to_sharded, batch)
        rng = self.accelerator.next_rng_key()
        self._stacked_params, self._stacked_opt, loss = self._local_step_jit(
            self._stacked_params, self._stacked_opt, batch, rng
        )
        self._step_count += 1
        if self._step_count % self.local_sgd_steps == 0:
            self._sync()
        return {"loss": loss}

    def _sync(self):
        self._stacked_params = self._average_jit(self._stacked_params)

    def __exit__(self, exc_type, exc, tb):
        if not self.enabled:
            return False
        self._sync()
        # Write the consensus replica back to the prepared model, restoring
        # its original (unstacked) shardings, and hand the optimizer its
        # state back (replica-averaged for float leaves — e.g. Adam moments —
        # shard 0's value for integer leaves like step counts).
        mean_params = jax.tree_util.tree_map(lambda p: p[0], self._stacked_params)
        self.model.load_state_dict(mean_params)
        self.optimizer.opt_state = jax.jit(
            lambda o: jax.tree_util.tree_map(
                lambda l: jnp.mean(l, axis=0)
                if jnp.issubdtype(jnp.asarray(l).dtype, jnp.inexact)
                else l[0],
                o,
            )
        )(self._stacked_opt)
        self._stacked_params = self._stacked_opt = None
        return False

    @property
    def num_local_steps(self) -> int:
        return self._step_count
