"""Learning-rate scheduler wrapper.

Capability parity with the reference's ``scheduler.py`` (reference:
src/accelerate/scheduler.py — AcceleratedScheduler :29: steps only when the
optimizer actually stepped; steps ``num_processes`` times unless
``split_batches`` :54-82).

JAX-native nuance: when the user builds their optax chain with a schedule
function, the LR already follows the *update count* (which equals applied
optimizer steps, so accumulation/skipped steps are handled for free). This
wrapper therefore (a) provides the familiar ``.step()/get_last_lr()``
surface, (b) supports runtime LR override via ``optax.inject_hyperparams``
states, and (c) keeps the reference's step-multiplier semantics for scripts
written against per-process batch counts.
"""

from __future__ import annotations

from typing import Callable, Optional

from .state import GradientState, PartialState


class LRScheduler:
    """Minimal native scheduler: a schedule fn + a counter."""

    def __init__(self, schedule_fn: Callable[[int], float]):
        self.schedule_fn = schedule_fn
        self.count = 0

    def step(self):
        """Advance the schedule by one step, unconditionally."""
        self.count += 1

    def get_last_lr(self):
        """Last computed learning rate(s), as a list (torch parity)."""
        return [float(self.schedule_fn(self.count))]

    def state_dict(self):
        """Host-side snapshot of the schedule position."""
        return {"count": self.count}

    def load_state_dict(self, sd):
        """Restore a state_dict snapshot."""
        self.count = sd.get("count", 0)


class AcceleratedScheduler:
    """Steps the wrapped scheduler in lockstep with real optimizer updates."""

    def __init__(
        self,
        scheduler,
        optimizers,
        step_with_optimizer: bool = True,
        split_batches: bool = False,
    ):
        self.scheduler = scheduler
        self.optimizers = optimizers if isinstance(optimizers, (list, tuple)) else [optimizers]
        self.step_with_optimizer = step_with_optimizer
        self.split_batches = split_batches
        self.gradient_state = GradientState()

    def step(self, *args, **kwargs):
        """Advance the schedule (gated to sync boundaries when prepared)."""
        if not self.step_with_optimizer:
            self.scheduler.step(*args, **kwargs)
            self._sync_lr_into_opt_states()
            return
        if not self.gradient_state.sync_gradients:
            # Accumulating: never advance the LR mid-accumulation (reference:
            # scheduler.py:61-64 — with adjust_scheduler the reference bumps a
            # torch-internal counter only to silence warnings; no LR change).
            return
        for opt in self.optimizers:
            if getattr(opt, "step_was_skipped", False):
                return
        if self.split_batches:
            self.scheduler.step(*args, **kwargs)
        else:
            # Reference semantics (:73-82): the user's schedule was written for
            # per-process progress; with a global batch num_processes× larger,
            # advance it num_processes times. Our host processes each drive
            # many chips; the multiplier is per *data-parallel host shard*.
            num_processes = PartialState().num_processes
            for _ in range(num_processes):
                self.scheduler.step(*args, **kwargs)
        self._sync_lr_into_opt_states()

    def _sync_lr_into_opt_states(self):
        """If an optimizer uses optax.inject_hyperparams, write the LR through."""
        if not hasattr(self.scheduler, "get_last_lr"):
            return
        try:
            lr = self.scheduler.get_last_lr()[0]
        except Exception:
            return
        for opt in self.optimizers:
            st = getattr(opt, "opt_state", None)
            hp = getattr(st, "hyperparams", None)
            if hp is not None and "learning_rate" in hp:
                import jax.numpy as jnp

                hp["learning_rate"] = jnp.asarray(lr, jnp.float32)

    def get_last_lr(self):
        """Last computed learning rate(s), as a list (torch parity)."""
        return self.scheduler.get_last_lr()

    def state_dict(self):
        """Host-side snapshot of the schedule position."""
        return self.scheduler.state_dict()

    def load_state_dict(self, sd):
        """Restore a state_dict snapshot."""
        self.scheduler.load_state_dict(sd)

    def get_lr(self):
        """Current learning rate(s) from the schedule function."""
        return self.scheduler.get_lr() if hasattr(self.scheduler, "get_lr") else self.get_last_lr()

    def __getattr__(self, name):
        return getattr(self.scheduler, name)
