"""Sharded data loading: host-local batches assembled into global jax.Arrays.

Capability parity with the reference's ``data_loader.py`` (reference:
src/accelerate/data_loader.py — SeedableRandomSampler :68, BatchSamplerShard
:101, IterableDatasetShard :257, DataLoaderStateMixin :356, DataLoaderShard
:491, DataLoaderDispatcher :676, prepare_data_loader :917, SkipBatchSampler
:1164, SkipDataLoader :1187, skip_first_batches :1215).

TPU-native redesign:

* The reference runs one process per accelerator and each process feeds its
  own device. Here one process per *host* feeds all local chips: each host
  loads its slice of the global batch and
  ``jax.make_array_from_process_local_data`` assembles the logical global
  array, sharded over the mesh's batch axes (dp×fsdp). GSPMD then moves
  shards as the compiled step requires — the reference's
  ``DataLoaderDispatcher`` broadcast machinery is subsumed by this, but a
  dispatcher variant (rank-0 reads, others receive) is still provided for
  non-shardable sources.
* Batches are staged host→device asynchronously with a configurable
  prefetch depth (double buffering), replacing torch_xla's MpDeviceLoader
  (reference: data_loader.py:626-673).
* ``end_of_dataloader``/``remainder`` bookkeeping feeds GradientState exactly
  like the reference (one-batch-lookahead iteration, :548-581).
"""

from __future__ import annotations

import math
import queue as queue_lib
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from .logging import get_logger
from .state import GradientState, PartialState
from .utils.dataclasses import DataLoaderConfiguration
from .utils.operations import find_batch_size, recursively_apply, send_to_device
from .utils.profiling import PipelineStats

logger = get_logger(__name__)


# ---------------------------------------------------------------------------
# Samplers (pure index math — runs on host, no jax involved)
# ---------------------------------------------------------------------------

class SeedableRandomSampler:
    """Deterministic random sampler whose order depends only on (seed, epoch)
    (reference: data_loader.py:68).

    Identical permutations on every process; sharding happens downstream in
    BatchSamplerShard.
    """

    def __init__(self, data_source_len: int, seed: int = 0, epoch: int = 0):
        self.data_source_len = data_source_len
        self.seed = seed
        self.epoch = epoch

    def __len__(self):
        return self.data_source_len

    def set_epoch(self, epoch: int):
        """Reseed samplers/generators for a new epoch (reference: set_epoch parity)."""
        self.epoch = epoch

    def __iter__(self) -> Iterator[int]:
        # Seed the generator on the (seed, epoch) *pair*, not their sum:
        # seed+epoch collides ((1, 0) == (0, 1)), replaying epoch orders
        # across runs that differ only in seed.
        rng = np.random.default_rng([self.seed, self.epoch])
        yield from rng.permutation(self.data_source_len).tolist()


class BatchSamplerShard:
    """Shards an index-batch stream across processes (reference: data_loader.py:101).

    Two modes, matching reference semantics exactly:

    * ``split_batches=False``: process ``i`` yields batches ``i, i+n, i+2n...``
      of the inner sampler (whose batch size is the *per-process* size).
    * ``split_batches=True``: every inner batch (of *global* size) is split in
      ``n`` chunks, process ``i`` taking chunk ``i``.

    ``even_batches=True`` pads the tail by cycling samples from the beginning
    so all processes see the same number of equal-size batches (reference
    :209-254); ``even_batches=False`` lets trailing processes receive fewer /
    smaller batches.
    """

    def __init__(
        self,
        batch_sampler: Iterable[list[int]],
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
        even_batches: bool = True,
    ):
        if split_batches:
            bs = getattr(batch_sampler, "batch_size", None)
            if bs is not None and bs % num_processes != 0:
                raise ValueError(
                    f"split_batches=True requires the batch size to divide evenly across "
                    f"processes, but {bs} is not divisible by {num_processes}."
                )
        self.batch_sampler = batch_sampler
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.even_batches = even_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)
        self.drop_last = getattr(batch_sampler, "drop_last", False)

    @property
    def total_length(self):
        """Number of batches in the underlying (unsharded) sampler."""
        return len(self.batch_sampler)

    def __len__(self):
        if self.split_batches:
            return len(self.batch_sampler)
        if len(self.batch_sampler) % self.num_processes == 0:
            return len(self.batch_sampler) // self.num_processes
        length = len(self.batch_sampler) // self.num_processes
        if self.drop_last:
            return length
        elif self.even_batches:
            return length + 1
        else:
            return length + 1 if self.process_index < len(self.batch_sampler) % self.num_processes else length

    def __iter__(self):
        return self._iter_with_split() if self.split_batches else self._iter_with_no_split()

    def _iter_with_split(self):
        # Each global batch is carved into num_processes chunks; the final,
        # possibly-incomplete batch is completed by cycling samples from the
        # first batch (reference :165-206).
        initial_data = []
        chunk_size = None
        for idx, batch in enumerate(self.batch_sampler):
            if idx == 0:
                initial_data = list(batch)
                chunk_size = len(batch) // self.num_processes
            if len(batch) == chunk_size * self.num_processes:
                yield batch[chunk_size * self.process_index : chunk_size * (self.process_index + 1)]
            elif not self.even_batches:
                chunk = batch[chunk_size * self.process_index : chunk_size * (self.process_index + 1)]
                if len(chunk) > 0:
                    yield chunk
            else:
                target = chunk_size * self.num_processes
                pad_src = initial_data if initial_data else list(batch)
                batch = list(batch)
                while len(batch) < target:
                    batch += pad_src[: target - len(batch)]
                yield batch[chunk_size * self.process_index : chunk_size * (self.process_index + 1)]

    def _iter_with_no_split(self):
        # Process i takes batch i of each round of num_processes batches. A
        # round only yields once complete; the final incomplete round (fewer
        # batches, or an undersized last batch) is rebuilt by flattening its
        # samples and cycling from the dataset start (reference :209-254,
        # matching the documented examples: range(26)/bs 4/2 procs ->
        # p0 [..., [24, 25, 0, 1]], p1 [..., [2, 3, 4, 5]]).
        initial_data: list = []
        current_round: list[list] = []
        idx = -1
        for idx, batch in enumerate(self.batch_sampler):
            if not self.drop_last and idx < self.num_processes:
                initial_data += batch
            current_round.append(batch)
            if idx % self.num_processes == self.num_processes - 1:
                if self.batch_size is None or len(batch) == self.batch_size:
                    yield current_round[self.process_index]
                    current_round = []
                # else: final round with undersized last batch — handled below.

        if self.drop_last or idx < 0 or not current_round:
            return
        if not self.even_batches:
            if len(current_round) > self.process_index:
                tail = current_round[self.process_index]
                if len(tail) > 0:
                    yield tail
            return
        bs = self.batch_size if self.batch_size is not None else len(current_round[0])
        flat = [i for b in current_round for i in b]
        pad_src = initial_data if initial_data else list(flat)
        while len(flat) < bs * self.num_processes:
            flat += pad_src[: bs * self.num_processes - len(flat)]
        yield flat[bs * self.process_index : bs * (self.process_index + 1)]


class IterableDatasetShard:
    """Shards an iterable dataset across processes (reference: data_loader.py:257).

    Buffers ``batch_size * num_processes`` items and yields this process's
    slice; the tail is padded by cycling from the first items when
    ``not drop_last`` (reference semantics).
    """

    def __init__(
        self,
        dataset: Iterable,
        batch_size: int = 1,
        drop_last: bool = False,
        num_processes: int = 1,
        process_index: int = 0,
        split_batches: bool = False,
    ):
        self.dataset = dataset
        self.batch_size = batch_size
        self.drop_last = drop_last
        self.num_processes = num_processes
        self.process_index = process_index
        self.split_batches = split_batches
        self.epoch = 0

    def set_epoch(self, epoch: int):
        """Reseed samplers/generators for a new epoch (reference: set_epoch parity)."""
        self.epoch = epoch
        if hasattr(self.dataset, "set_epoch"):
            self.dataset.set_epoch(epoch)

    def __len__(self):
        if self.drop_last:
            return (len(self.dataset) // (self.batch_size * self.num_processes)) * self.batch_size
        else:
            return math.ceil(len(self.dataset) / (self.batch_size * self.num_processes)) * self.batch_size

    def __iter__(self):
        real_batch_size = self.batch_size if self.split_batches else self.batch_size * self.num_processes
        process_batch_size = self.batch_size // self.num_processes if self.split_batches else self.batch_size
        process_slice = range(self.process_index * process_batch_size, (self.process_index + 1) * process_batch_size)

        first_batch = None
        current_batch = []
        for element in self.dataset:
            current_batch.append(element)
            if len(current_batch) == real_batch_size:
                for i in process_slice:
                    yield current_batch[i]
                if first_batch is None:
                    first_batch = current_batch.copy()
                current_batch = []

        if not self.drop_last and len(current_batch) > 0:
            if first_batch is None:
                first_batch = current_batch.copy()
            while len(current_batch) < real_batch_size:
                current_batch += first_batch
            for i in process_slice:
                yield current_batch[i]


# ---------------------------------------------------------------------------
# Device staging
# ---------------------------------------------------------------------------

def _concat_numpy_batches(batches: list):
    """Leafwise concatenation of several batch pytrees along dim 0."""
    first = batches[0]
    if isinstance(first, dict):
        return {k: _concat_numpy_batches([b[k] for b in batches]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(_concat_numpy_batches([b[i] for b in batches]) for i in range(len(first)))
    return np.concatenate([np.asarray(b) for b in batches], axis=0)


def default_collate(samples: list[Any]):
    """Stack a list of samples into a batch pytree (numpy)."""
    first = samples[0]
    if isinstance(first, dict):
        return {k: default_collate([s[k] for s in samples]) for k in first}
    if isinstance(first, (tuple, list)):
        return type(first)(default_collate([s[i] for s in samples]) for i in range(len(first)))
    arrs = [np.asarray(s) for s in samples]
    return np.stack(arrs)


def batch_sharding(mesh):
    """NamedSharding for batches: leading dim split over the batch axes."""
    from jax.sharding import NamedSharding, PartitionSpec

    from .utils.constants import BATCH_AXES

    axes = tuple(ax for ax in BATCH_AXES if ax in mesh.shape)
    return NamedSharding(mesh, PartitionSpec(axes))


def make_global_batch(local_batch, mesh, sharding=None):
    """Assemble per-host numpy batches into a global sharded jax.Array
    (replaces the reference's per-device ``send_to_device``, data_loader.py:566)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    sharding = sharding or batch_sharding(mesh)
    replicated = NamedSharding(mesh, PartitionSpec())
    n_shards = 1
    spec0 = sharding.spec[0] if isinstance(sharding, NamedSharding) and len(sharding.spec) else None
    if spec0 is not None:
        axes = (spec0,) if isinstance(spec0, str) else tuple(spec0)
        n_shards = math.prod(mesh.shape[ax] for ax in axes)

    def _make(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            # x IS the global batch. Leaves whose batch dim doesn't divide
            # the batch axes (scalars, odd tails) replicate instead.
            sh = sharding if (x.ndim > 0 and n_shards > 1 and x.shape[0] % n_shards == 0) else replicated
            return jax.device_put(x, sh)
        # Multi-process: x is only this process's contribution; the global
        # batch is the rank-order concatenation, so divisibility must be
        # judged on the GLOBAL row count.
        global_rows = x.shape[0] * jax.process_count() if x.ndim > 0 else 0
        if x.ndim > 0 and n_shards > 1 and global_rows % n_shards == 0:
            try:
                return jax.make_array_from_process_local_data(sharding, x)
            except ValueError:
                pass  # local rows don't tile this process's shards: replicate
        if x.ndim == 0:
            # Scalar leaves are host-synchronized by contract (same value fed
            # on every process); replicate directly.
            return jax.make_array_from_process_local_data(replicated, x)
        # Replicated fallback: build the TRUE global value first. Feeding the
        # local shard under a replicated sharding would silently give every
        # process a different "global" array — per-process training, no error.
        from jax.experimental import multihost_utils

        full = np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return jax.make_array_from_process_local_data(replicated, full)

    return recursively_apply(_make, local_batch)


# ---------------------------------------------------------------------------
# Asynchronous prefetch pipeline
# ---------------------------------------------------------------------------

class _EndOfStream:
    """Queue sentinel: the producer exhausted its source."""


_END = _EndOfStream()


class _PipelineError:
    """Queue envelope carrying a producer-side exception to the consumer."""

    __slots__ = ("exc",)

    def __init__(self, exc: BaseException):
        self.exc = exc


class _Ready:
    """Future-alike for already-staged batches (single-worker path)."""

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def result(self):
        return self._value


class AsyncPrefetcher:
    """Background input pipeline: a puller thread drains ``produce`` (fetch +
    collate on the training data source), stages each batch (host→device),
    and parks up to ``prefetch_size`` staged batches in a bounded queue.

    This is what actually overlaps host input work with device compute:
    JAX's async dispatch lets the device run ahead of the host, but only if
    the host thread isn't busy collating the next batch — here that work
    happens on the worker while the training thread is inside the step.

    * ``produce`` is a zero-arg callable returning the next raw host batch
      and raising ``StopIteration`` when the source is exhausted. Pulling is
      inherently serial (it's an iterator), so there is exactly one puller
      thread regardless of ``num_workers``.
    * ``num_workers > 1`` parallelizes the *staging* (collate pytrees +
      ``jax.make_array_from_process_local_data``) across a thread pool; the
      bounded queue holds futures in pull order, so batch order is always
      preserved and backpressure still applies.
    * Producer exceptions are forwarded and re-raised in the consumer.
    * ``close()`` is idempotent and safe mid-epoch: it wakes a blocked
      puller, joins the thread, and tears down the pool, so abandoning an
      iterator (``break`` mid-epoch, GC) never leaks a worker.
    """

    def __init__(
        self,
        produce: Callable[[], Any],
        stage: Callable[[Any], Any],
        prefetch_size: int = 2,
        num_workers: int = 1,
        stats: Optional[PipelineStats] = None,
    ):
        self._produce = produce
        self._stage = stage
        self._stats = stats
        self._queue: queue_lib.Queue = queue_lib.Queue(maxsize=max(1, prefetch_size))
        self._stop = threading.Event()
        self._closed = False
        self._executor = ThreadPoolExecutor(
            max_workers=num_workers, thread_name_prefix="atpu-stage"
        ) if num_workers > 1 else None
        self._thread = threading.Thread(
            target=self._run, name="atpu-prefetch", daemon=True
        )
        self._thread.start()

    # -- producer side ------------------------------------------------------

    def _timed_stage(self, raw):
        import time

        t0 = time.perf_counter()
        out = self._stage(raw)
        if self._stats is not None:
            self._stats.record_stage((time.perf_counter() - t0) * 1e3)
        return out

    def _put(self, item) -> bool:
        # Bounded-blocking put that stays responsive to close(): a plain
        # Queue.put would deadlock the worker against a consumer that left.
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue_lib.Full:
                continue
        return False

    def _run(self):
        try:
            while not self._stop.is_set():
                try:
                    raw = self._produce()
                except StopIteration:
                    break
                if self._executor is not None:
                    item = self._executor.submit(self._timed_stage, raw)
                else:
                    item = _Ready(self._timed_stage(raw))
                if not self._put(item):
                    return
        except BaseException as exc:  # noqa: BLE001 - forwarded, not swallowed
            self._put(_PipelineError(exc))
            return
        self._put(_END)

    # -- consumer side ------------------------------------------------------

    def get(self):
        """Next staged batch in source order. Raises ``StopIteration`` at end
        of stream and re-raises any producer-side exception."""
        import time

        t0 = time.perf_counter()
        item = self._queue.get()
        if isinstance(item, _PipelineError):
            self._stop.set()
            raise item.exc
        if item is _END:
            raise StopIteration
        batch = item.result()  # blocks iff staging (num_workers>1) lags
        if self._stats is not None:
            self._stats.record_wait((time.perf_counter() - t0) * 1e3)
            self._stats.record_depth(self._queue.qsize())
        return batch

    def close(self):
        """Stop the worker and release every pipeline resource (idempotent)."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        # Drain so a put()-blocked worker wakes immediately.
        try:
            while True:
                self._queue.get_nowait()
        except queue_lib.Empty:
            pass
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            # A hung stage/produce call (slow host->device transfer, blocked
            # broadcast) keeps the worker alive past the join timeout — and
            # still pulling from the base iterator. Opening a new epoch now
            # stacks a second live worker on the same source; make that
            # visible instead of leaking silently.
            logger.warning(
                "atpu-prefetch worker still alive 5s after close(); a "
                "produce/stage call is hung and the worker keeps consuming "
                "the base iterator until it returns. Each new epoch will "
                "add another live worker.",
                main_process_only=False,
            )
        if self._executor is not None:
            self._executor.shutdown(wait=False, cancel_futures=True)

    def __del__(self):  # pragma: no cover - GC-timing dependent
        self.close()


# ---------------------------------------------------------------------------
# DataLoader wrappers
# ---------------------------------------------------------------------------

class DataLoaderStateMixin:
    """Tracks end_of_dataloader/remainder and registers with GradientState
    (reference: data_loader.py:356)."""

    def __init_subclass__(cls, **kwargs):
        cls.end_of_dataloader = False
        cls.remainder = -1

    def reset(self):
        """Clear end-of-epoch bookkeeping."""
        self.end_of_dataloader = False
        self.remainder = -1

    def begin(self):
        """Register with GradientState and compute the tail remainder at epoch start."""
        self.reset()
        with suppress_exceptions():
            length = getattr(self.base_dataloader, "total_dataset_length", len(self.dataset))
            self.remainder = length % self.total_batch_size
        self.gradient_state._add_dataloader(self)

    def end(self):
        """Deregister from GradientState at epoch end."""
        self.gradient_state._remove_dataloader(self)


class suppress_exceptions:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return True


class DataLoaderShard(DataLoaderStateMixin):
    """Per-host loader producing global sharded device batches
    (reference: data_loader.py:491).

    Wraps any iterable yielding host-local numpy batch pytrees. Iteration:

    * synchronizes host RNG streams once per epoch (reference :549)
    * iterates one batch ahead to set ``end_of_dataloader`` on the last one
    * assembles global jax.Arrays sharded over the mesh batch axes
    * with ``async_prefetch`` (the default) a background worker pulls,
      collates, and stages up to ``prefetch_size`` batches ahead of the
      training thread (:class:`AsyncPrefetcher`), overlapping host input
      work with device compute; ``async_prefetch=False`` falls back to
      inline staging with the same prefetch-depth lookahead
    * records ``data_wait_ms``/``stage_ms``/queue-depth into
      :attr:`pipeline_stats` either way, so step-time breakdowns are
      comparable across modes
    """

    def __init__(
        self,
        base_dataloader: Iterable,
        mesh=None,
        device_sharding=None,
        rng_types: Optional[list[str]] = None,
        synchronized_generator=None,
        skip_batches: int = 0,
        prefetch_size: int = 2,
        total_batch_size: Optional[int] = None,
        dataset_length: Optional[int] = None,
        stage_to_device: bool = True,
        async_prefetch: bool = True,
        num_workers: int = 1,
        _non_blocking: bool = True,
        **kwargs,
    ):
        self.base_dataloader = base_dataloader
        self.mesh = mesh
        self.device_sharding = device_sharding
        self.rng_types = rng_types
        self.synchronized_generator = synchronized_generator
        self.skip_batches = skip_batches
        self.prefetch_size = max(1, prefetch_size)
        self.async_prefetch = async_prefetch
        self.num_workers = max(1, num_workers)
        self.stage_to_device = stage_to_device and mesh is not None
        self.gradient_state = GradientState()
        self.pipeline_stats = PipelineStats()
        self._total_batch_size = total_batch_size
        self._dataset_length = dataset_length
        self.iteration = 0  # epoch counter
        self.batches_consumed = 0  # within current epoch, for resume

    @property
    def dataset(self):
        """The underlying dataset (or a length-only stand-in)."""
        inner = getattr(self.base_dataloader, "dataset", None)
        if inner is not None:
            return inner
        if self._dataset_length is not None:
            class _Sized:
                def __init__(s, n):
                    s._n = n

                def __len__(s):
                    return s._n

            return _Sized(self._dataset_length)
        raise AttributeError("dataset")

    @property
    def total_batch_size(self):
        """Global batch size across all processes (reference: data_loader.py:600)."""
        if self._total_batch_size is not None:
            return self._total_batch_size
        bs = getattr(self.base_dataloader, "batch_size", None)
        if bs is None:
            sampler = getattr(self.base_dataloader, "batch_sampler", None)
            bs = getattr(sampler, "batch_size", None)
        if bs is None:
            return 1
        return bs * PartialState().num_processes

    @property
    def total_dataset_length(self):
        """len(dataset), or None for unsized iterables."""
        try:
            return len(self.dataset)
        except (TypeError, AttributeError):
            return None

    def set_epoch(self, epoch: int):
        """Reseed samplers/generators for a new epoch (reference: set_epoch parity)."""
        self.iteration = epoch
        if self.synchronized_generator is not None and hasattr(self.synchronized_generator, "set_epoch"):
            self.synchronized_generator.set_epoch(epoch)
        sampler = getattr(self.base_dataloader, "sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)
        batch_sampler = getattr(self.base_dataloader, "batch_sampler", None)
        inner = getattr(batch_sampler, "batch_sampler", batch_sampler)
        if inner is not None and hasattr(inner, "set_epoch"):
            inner.set_epoch(epoch)
        if hasattr(self.base_dataloader, "set_epoch"):
            self.base_dataloader.set_epoch(epoch)

    def _stage(self, batch):
        if not self.stage_to_device:
            return batch
        from .utils.profiling import annotate

        with annotate("atpu:stage_batch"):
            return make_global_batch(batch, self.mesh, self.device_sharding)

    def _produce_fn(self) -> Callable[[], Any]:
        """Zero-arg producer for this epoch: fetch-only skip on resume, then
        raw host batches. Skipped batches are never staged — and resume
        counting (``batches_consumed``) only ever counts *yielded* batches,
        so prefetched-but-unconsumed batches don't poison ``state_dict``."""
        raw_iter = iter(self.base_dataloader)
        for _ in range(self.skip_batches):
            try:
                next(raw_iter)
            except StopIteration:
                break
        return lambda: next(raw_iter)

    def _sync_staged_stream(self, produce):
        """Inline fallback: same prefetch-depth lookahead as before, staged on
        the training thread (reference :548-581 + MpDeviceLoader double
        buffering). Wait time here IS produce+stage time — the serialized
        cost the async path removes — so the metric stays comparable."""
        def pull():
            with self.pipeline_stats.time_wait():
                raw = produce()
                with self.pipeline_stats.time_stage():
                    return self._stage(raw)

        staged: deque = deque()
        exhausted = False
        while not exhausted and len(staged) < self.prefetch_size:
            try:
                staged.append(pull())
            except StopIteration:
                exhausted = True
        while staged:
            if not exhausted:
                try:
                    staged.append(pull())
                except StopIteration:
                    exhausted = True
            yield staged.popleft()

    def _async_staged_stream(self, produce):
        """Staged batches from the background pipeline, in source order."""
        prefetcher = AsyncPrefetcher(
            produce,
            self._stage,
            prefetch_size=self.prefetch_size,
            num_workers=self.num_workers,
            stats=self.pipeline_stats,
        )
        try:
            while True:
                try:
                    batch = prefetcher.get()
                except StopIteration:
                    return
                yield batch
        finally:
            prefetcher.close()

    def _use_async_prefetch(self) -> bool:
        """Whether this epoch's stream runs on the background worker.
        Subclasses veto the async path when their producer cannot safely run
        off the training thread (see DataLoaderDispatcher)."""
        return self.async_prefetch

    def _iterate(self, produce):
        """One-ahead loop shared by Shard and Dispatcher: the GradientState
        flags flip on the final batch *before* it is yielded, identically in
        sync and async modes."""
        stream = (
            self._async_staged_stream(produce)
            if self._use_async_prefetch()
            else self._sync_staged_stream(produce)
        )
        try:
            current = next(stream, _END)
            while current is not _END:
                nxt = next(stream, _END)
                if nxt is _END:
                    self.end_of_dataloader = True
                    self.gradient_state._set_sync_gradients(True)
                self.batches_consumed += 1
                yield current
                current = nxt
        finally:
            stream.close()  # tears down the worker even on abandoned iterators
            if self.end_of_dataloader:
                # Epoch completed: resume starts the next epoch from batch 0.
                self.batches_consumed = 0
            self.iteration += 1
            self.skip_batches = 0
            self.end()

    def __iter__(self):
        from .utils.random import synchronize_rng_states

        if self.rng_types is not None:
            synchronize_rng_states(self.rng_types, self.synchronized_generator)
        self.begin()
        self.set_epoch(self.iteration)
        self.batches_consumed = self.skip_batches
        yield from self._iterate(self._produce_fn())

    def __len__(self):
        # Clamped: skip_batches beyond the epoch must read as empty, not a
        # negative length.
        return max(0, len(self.base_dataloader) - (self.skip_batches or 0))

    # -- resume support (reference: DataLoaderAdapter.state_dict :448) -------
    def state_dict(self) -> dict:
        """Resume position: epoch counter + batches consumed."""
        return {
            "epoch": self.iteration,
            "batches_consumed": self.batches_consumed,
        }

    def load_state_dict(self, sd: dict):
        """Restore a resume position recorded by state_dict."""
        self.iteration = sd.get("epoch", 0)
        self.skip_batches = sd.get("batches_consumed", 0)


class DataLoaderDispatcher(DataLoaderShard):
    """Process 0 reads data; others receive the broadcast slice
    (reference: data_loader.py:676-856).

    For sources that only exist on one host (e.g. a stream). Each batch incurs
    a host-network broadcast — prefer DataLoaderShard when every host can read
    its slice.

    Async prefetch is forced off in multi-process runs: the broadcast is a
    device collective, and issuing it from the prefetch thread would
    interleave nondeterministically with the training step's collectives on
    the shared devices — each process could enqueue (broadcast, step) in a
    different order, mismatching collectives and deadlocking the slice. See
    :meth:`_use_async_prefetch`.
    """

    def __init__(self, *args, split_batches: bool = False, **kwargs):
        super().__init__(*args, **kwargs)
        self.split_batches = split_batches

    @property
    def total_batch_size(self):
        """With split_batches the base batch IS the global batch; otherwise
        the dispatcher concatenates one base batch per process (reference:
        data_loader.py:735-856 fetch semantics)."""
        if self._total_batch_size is not None:
            return self._total_batch_size
        bs = getattr(self.base_dataloader, "batch_size", None) or 1
        return bs if self.split_batches else bs * PartialState().num_processes

    def _fetch_and_broadcast(self, raw_iter):
        from .utils.operations import broadcast_object_list

        state = PartialState()
        n_fetch = 1 if self.split_batches else state.num_processes
        if state.is_main_process:
            fetched = []
            for _ in range(n_fetch):
                try:
                    fetched.append(next(raw_iter))
                except StopIteration:
                    break
            if not fetched:
                payload = [1, None]
            else:
                batch = fetched[0] if len(fetched) == 1 else _concat_numpy_batches(fetched)
                payload = [0, batch]
        else:
            payload = [None, None]
        if state.num_processes > 1:
            payload = broadcast_object_list(payload)
        if payload[0] == 1:
            raise StopIteration
        batch = payload[1]
        # Slice this host's portion of the global batch.
        if state.num_processes > 1:
            bs = find_batch_size(batch)
            per = bs // state.num_processes
            lo, hi = per * state.process_index, per * (state.process_index + 1)
            batch = recursively_apply(lambda t: t[lo:hi], batch)
        return batch

    def _use_async_prefetch(self) -> bool:
        """Multi-process dispatch must fetch/broadcast on the consumer
        thread: broadcast_object_list is a device collective, and a
        background thread would race it against the step's collectives —
        worker-vs-worker ordering is serial (single puller), but
        worker-vs-training-thread ordering on the shared devices is not
        deterministic across processes. Single-process dispatch issues no
        collective, so it keeps the async pipeline."""
        return self.async_prefetch and PartialState().num_processes == 1

    def _produce_fn(self) -> Callable[[], Any]:
        """Producer = fetch-on-rank-0 + broadcast. In multi-process runs
        this only ever runs on the training thread (_use_async_prefetch
        vetoes the worker) so the broadcast keeps a deterministic order
        relative to the step's collectives."""
        raw_iter = iter(self.base_dataloader) if PartialState().is_main_process else iter(())
        for _ in range(self.skip_batches):
            try:
                self._fetch_and_broadcast(raw_iter)
            except StopIteration:
                break
        return lambda: self._fetch_and_broadcast(raw_iter)

    def __iter__(self):
        self.begin()
        self.set_epoch(self.iteration)
        self.batches_consumed = self.skip_batches
        yield from self._iterate(self._produce_fn())


# ---------------------------------------------------------------------------
# Simple native loader (no torch required)
# ---------------------------------------------------------------------------

class NumpyDataLoader:
    """Minimal map-style loader: dataset (len + __getitem__) -> numpy batches.

    The native counterpart of torch.utils.data.DataLoader for users who don't
    bring torch. Supports shuffle (seedable), drop_last, and a collate_fn.
    """

    def __init__(
        self,
        dataset,
        batch_size: int = 1,
        shuffle: bool = False,
        drop_last: bool = False,
        collate_fn: Callable = default_collate,
        seed: int = 0,
        sampler=None,
        batch_sampler=None,
    ):
        self.dataset = dataset
        self.batch_size = batch_size if batch_sampler is None else getattr(batch_sampler, "batch_size", batch_size)
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.collate_fn = collate_fn
        self.sampler = sampler if sampler is not None else (
            SeedableRandomSampler(len(dataset), seed=seed) if shuffle else range(len(dataset))
        )
        self.batch_sampler = batch_sampler

    def set_epoch(self, epoch: int):
        """Reseed samplers/generators for a new epoch (reference: set_epoch parity)."""
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def _index_batches(self):
        if self.batch_sampler is not None:
            yield from self.batch_sampler
            return
        batch = []
        for i in self.sampler:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __iter__(self):
        for idxs in self._index_batches():
            yield self.collate_fn([self.dataset[i] for i in idxs])

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        n = len(self.sampler) if hasattr(self.sampler, "__len__") else len(self.dataset)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


class BatchSamplerFromSampler:
    """Group a sampler's indices into batches (torch BatchSampler equivalent)."""

    def __init__(self, sampler, batch_size: int, drop_last: bool = False):
        self.sampler = sampler
        self.batch_size = batch_size
        self.drop_last = drop_last

    def set_epoch(self, epoch: int):
        """Reseed samplers/generators for a new epoch (reference: set_epoch parity)."""
        if hasattr(self.sampler, "set_epoch"):
            self.sampler.set_epoch(epoch)

    def __iter__(self):
        batch = []
        for i in self.sampler:
            batch.append(i)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        return n // self.batch_size if self.drop_last else math.ceil(n / self.batch_size)


# ---------------------------------------------------------------------------
# prepare_data_loader (reference: data_loader.py:917)
# ---------------------------------------------------------------------------

def _is_torch_dataloader(obj) -> bool:
    try:
        from torch.utils.data import DataLoader  # type: ignore

        return isinstance(obj, DataLoader)
    except ImportError:
        return False


def prepare_data_loader(
    dataloader,
    mesh=None,
    device_sharding=None,
    num_processes: Optional[int] = None,
    process_index: Optional[int] = None,
    split_batches: bool = False,
    put_on_device: bool = True,
    rng_types: Optional[list[str]] = None,
    dispatch_batches: Optional[bool] = None,
    even_batches: bool = True,
    slice_fn_for_dispatch=None,
    use_seedable_sampler: bool = True,
    data_seed: Optional[int] = None,
    non_blocking: bool = True,
    use_stateful_dataloader: bool = True,
    prefetch_size: int = 2,
    skip_batches: int = 0,
    async_prefetch: bool = True,
    num_workers: int = 1,
) -> DataLoaderShard:
    """Shard any dataloader across processes and stage batches to the mesh
    (reference: data_loader.py:917-1161).

    Accepts a torch ``DataLoader``, a :class:`NumpyDataLoader`, or any
    iterable of batch pytrees. Re-batching semantics match the reference:
    with ``split_batches=False`` each process keeps the original batch size
    (global batch = batch_size × num_processes); with True the given batch
    size is global and gets split.
    """
    state = PartialState()
    num_processes = num_processes if num_processes is not None else state.num_processes
    process_index = process_index if process_index is not None else state.process_index
    if dispatch_batches is None:
        dispatch_batches = False

    if dispatch_batches:
        return DataLoaderDispatcher(
            dataloader,
            mesh=mesh,
            device_sharding=device_sharding,
            rng_types=rng_types,
            prefetch_size=prefetch_size,
            skip_batches=skip_batches,
            stage_to_device=put_on_device,
            async_prefetch=async_prefetch,
            num_workers=num_workers,
        )

    new_loader = dataloader
    synchronized_generator = None

    if num_processes > 1:
        if _is_torch_dataloader(dataloader):
            new_loader = _reshard_torch_dataloader(
                dataloader, num_processes, process_index, split_batches, even_batches,
                use_seedable_sampler, data_seed,
            )
        elif isinstance(dataloader, NumpyDataLoader):
            inner_bs = BatchSamplerFromSampler(dataloader.sampler, dataloader.batch_size, dataloader.drop_last)
            shard = BatchSamplerShard(
                inner_bs, num_processes=num_processes, process_index=process_index,
                split_batches=split_batches, even_batches=even_batches,
            )
            if isinstance(dataloader.sampler, SeedableRandomSampler):
                synchronized_generator = dataloader.sampler
            new_loader = NumpyDataLoader(
                dataloader.dataset,
                batch_size=(dataloader.batch_size // num_processes) if split_batches else dataloader.batch_size,
                collate_fn=dataloader.collate_fn,
                batch_sampler=shard,
            )
        # generic iterables: assume already host-sharded (each process reads its slice)

    return DataLoaderShard(
        new_loader,
        mesh=mesh,
        device_sharding=device_sharding,
        rng_types=rng_types,
        synchronized_generator=synchronized_generator,
        skip_batches=skip_batches,
        prefetch_size=prefetch_size,
        async_prefetch=async_prefetch,
        num_workers=num_workers,
        stage_to_device=put_on_device,
        total_batch_size=(
            getattr(dataloader, "batch_size", None)
            if split_batches
            else (getattr(dataloader, "batch_size", None) or 1) * num_processes
        ),
    )


def _reshard_torch_dataloader(dataloader, num_processes, process_index, split_batches,
                              even_batches, use_seedable_sampler, data_seed):
    """Rebuild a torch DataLoader with a sharded batch sampler."""
    from torch.utils.data import DataLoader  # type: ignore

    batch_sampler = dataloader.batch_sampler
    shard = BatchSamplerShard(
        batch_sampler,
        num_processes=num_processes,
        process_index=process_index,
        split_batches=split_batches,
        even_batches=even_batches,
    )
    kwargs = {
        "num_workers": dataloader.num_workers,
        "collate_fn": dataloader.collate_fn,
        "pin_memory": False,
        "timeout": dataloader.timeout,
        "worker_init_fn": dataloader.worker_init_fn,
    }
    return DataLoader(dataloader.dataset, batch_sampler=shard, **kwargs)


# ---------------------------------------------------------------------------
# skip_first_batches (reference: data_loader.py:1215)
# ---------------------------------------------------------------------------

class SkipBatchSampler:
    """Yields batches of an inner batch sampler after the first N
    (reference: data_loader.py:1164)."""

    def __init__(self, batch_sampler, skip_batches: int = 0):
        self.batch_sampler = batch_sampler
        self.skip_batches = skip_batches
        self.batch_size = getattr(batch_sampler, "batch_size", None)

    def __iter__(self):
        for index, samples in enumerate(self.batch_sampler):
            if index >= self.skip_batches:
                yield samples

    @property
    def total_length(self):
        """Number of batches in the underlying (unsharded) sampler."""
        return len(self.batch_sampler)

    def __len__(self):
        return len(self.batch_sampler) - self.skip_batches


class SkipDataLoader:
    """Iterable skipping the first N batches (reference: data_loader.py:1187)."""

    def __init__(self, dataloader, skip_batches: int = 0):
        self.dataloader = dataloader
        self.skip_batches = skip_batches
        self.dataset = getattr(dataloader, "dataset", None)
        self.batch_size = getattr(dataloader, "batch_size", None)

    def __iter__(self):
        for index, batch in enumerate(self.dataloader):
            if index >= self.skip_batches:
                yield batch

    def __len__(self):
        return len(self.dataloader) - self.skip_batches


def skip_first_batches(dataloader, num_batches: int = 0):
    """Resume mid-epoch: a loader that skips the first ``num_batches``
    (reference: data_loader.py:1215)."""
    if isinstance(dataloader, DataLoaderShard):
        import copy

        new = copy.copy(dataloader)
        new.skip_batches = num_batches
        return new
    return SkipDataLoader(dataloader, skip_batches=num_batches)


def pack_sequences(sequences, seq_len: int, pad_token_id: int = 0):
    """Pack variable-length token sequences into fixed [N, seq_len] rows.

    The training-throughput alternative to padding each document: documents
    are greedily first-fit packed into rows; the returned batch carries
    everything the models need to keep them independent:

    * ``input_ids``   [N, L] — concatenated documents + trailing pad
    * ``segment_ids`` [N, L] — 1, 2, ... per document, 0 on padding; the
      attention mask (ops/attention.py segment semantics) blocks
      cross-document attention
    * ``positions``   [N, L] — restart at 0 for each document, so RoPE sees
      every document at its own offsets
    * ``labels``      [N, L] — next token *within* the document; -100 (the
      ignored-index convention) at document boundaries and padding

    Documents longer than ``seq_len`` are split into ``seq_len`` chunks
    first (each chunk becomes its own segment). Use with
    ``causal_lm_loss``/``fused_causal_lm_loss`` over a Llama-family model —
    they forward positions/segment_ids automatically (other families'
    apply signatures don't take these kwargs). Segment masking rides the
    einsum attention path; backend "auto" falls back to it when
    segment_ids are present.
    """
    chunks = []
    for seq in sequences:
        arr = np.asarray(seq, dtype=np.int32).reshape(-1)
        for start in range(0, len(arr), seq_len):
            piece = arr[start:start + seq_len]
            if len(piece) > 0:
                chunks.append(piece)
    # Best-fit-decreasing via a bisect-sorted free list: O(n log n) in
    # document count (a linear first-fit scan is quadratic — hours of
    # Python for a 1M-doc corpus).
    import bisect

    rows: list[list[np.ndarray]] = []
    free_sorted: list[tuple[int, int]] = []  # (free_space, row_index), sorted
    for piece in sorted(chunks, key=len, reverse=True):
        j = bisect.bisect_left(free_sorted, (len(piece), -1))
        if j < len(free_sorted):
            free, r = free_sorted.pop(j)
            rows[r].append(piece)
            if free - len(piece) > 0:
                bisect.insort(free_sorted, (free - len(piece), r))
        else:
            rows.append([piece])
            if seq_len - len(piece) > 0:
                bisect.insort(free_sorted, (seq_len - len(piece), len(rows) - 1))

    N = len(rows)
    input_ids = np.full((N, seq_len), pad_token_id, np.int32)
    segment_ids = np.zeros((N, seq_len), np.int32)
    positions = np.zeros((N, seq_len), np.int32)
    labels = np.full((N, seq_len), -100, np.int32)
    for r, pieces in enumerate(rows):
        offset = 0
        for s, piece in enumerate(pieces, start=1):
            n = len(piece)
            input_ids[r, offset:offset + n] = piece
            segment_ids[r, offset:offset + n] = s
            positions[r, offset:offset + n] = np.arange(n)
            # next-token labels stay inside the document
            labels[r, offset:offset + n - 1] = piece[1:]
            offset += n
    return {"input_ids": input_ids, "segment_ids": segment_ids,
            "positions": positions, "labels": labels}
