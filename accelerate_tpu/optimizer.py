"""Optimizer wrapper over optax.

Capability parity with the reference's ``optimizer.py`` (reference:
src/accelerate/optimizer.py — AcceleratedOptimizer :38: skips step/zero_grad
during accumulation :112/:155, grad-scaler step with skipped-step detection
:155-170, XLA grad all-reduce before step :142-148).

TPU-native redesign: the optimizer is an optax GradientTransformation; this
wrapper owns the (sharded) ``opt_state`` and a device-side gradient
accumulator. Cross-device gradient reduction needs NO explicit all-reduce —
the loss is a mean over the global (sharded) batch inside jit, so XLA emits
the reduction as part of the backward pass (the reference's
``xm.all_reduce`` at optimizer.py:142-148 has no equivalent here by design).

fp16 loss scaling is a pure state transition (precision.py) applied inside
the jitted step with a ``lax.cond``-style select: non-finite grads skip the
update and back off the scale, exactly like torch GradScaler.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .precision import (
    GradScalerKwargs,
    LossScaleState,
    grads_finite,
    make_loss_scale,
    unscale_grads,
    update_loss_scale,
)
from .state import GradientState


class AcceleratedOptimizer:
    """Wraps an optax transformation with accumulation/scaling/skip logic.

    Created by ``Accelerator.prepare``; not usually constructed directly.
    """

    def __init__(
        self,
        tx,                                  # optax.GradientTransformation
        params=None,                         # initial params (to init opt_state)
        param_shardings=None,
        scaler_kwargs: Optional[GradScalerKwargs] = None,
        use_loss_scaling: bool = False,
        mesh=None,
        offload_to_host: bool = False,
        zero_sharding: bool = False,
        zero_min_size_to_shard: int = 2**11,
    ):
        self.tx = tx
        self.gradient_state = GradientState()
        self.mesh = mesh
        self.param_shardings = param_shardings
        self.offload_to_host = offload_to_host
        #: ZeRO-1/2: partition moment tensors over the dp (or fsdp) axis so
        #: each replica stores/updates 1/dp of the state (sharding.py
        #: infer_opt_state_shardings). Populated into opt_state_shardings at
        #: init_state time; the jitted update then carries explicit in/out
        #: shardings so GSPMD reduce-scatters grads, updates the local shard,
        #: and all-gathers params.
        self.zero_sharding = zero_sharding
        self.zero_min_size_to_shard = zero_min_size_to_shard
        self.opt_state_shardings = None
        self.opt_state = None
        self.acc_grads = None
        self._accumulated = 0
        self.scaler_kwargs = scaler_kwargs or GradScalerKwargs()
        self.loss_scale: Optional[LossScaleState] = make_loss_scale(self.scaler_kwargs, enabled=use_loss_scaling)
        self._step_was_skipped = False
        self._steps_applied = 0
        self._model = None  # back-ref set by Accelerator.prepare
        self._apply_jit = None
        self._grads_already_unscaled = False  # set by clip_grad_norm_ (fp16)
        # Fused-step bookkeeping: device-side finite flags, drained lazily so
        # the hot loop never forces a host sync (see steps_applied property).
        self._pending_finite: list = []
        self._last_finite = None
        if params is not None:
            self.init_state(params)

    # ------------------------------------------------------------------
    def init_state(self, params):
        """Initialize (sharded) optimizer state.

        opt_state leaves that mirror params (mu/nu) inherit the param
        shardings via jit's sharding propagation: we init under jit with
        out_shardings left to GSPMD.

        Models containing fp8 statistics params (ops/quant.py Fp8Dense) get
        the optimizer partitioned automatically: statistics leaves are
        overwritten with their updated values, never Adam-stepped.
        """
        from .ops.quant import wrap_optimizer_for_fp8

        if not getattr(self, "_fp8_wrapped", False):
            wrapped = wrap_optimizer_for_fp8(self.tx, params)
            if wrapped is not self.tx:
                self.tx = wrapped
                self._fp8_wrapped = True
        if self.param_shardings is not None:
            init = jax.jit(self.tx.init)
            self.opt_state = init(params)
        else:
            self.opt_state = self.tx.init(params)
        if self.zero_sharding and self.mesh is not None and (
            self.mesh.shape.get("dp", 1) > 1 or self.mesh.shape.get("fsdp", 1) > 1
        ):
            from .parallel.sharding import infer_opt_state_shardings

            self.opt_state_shardings = infer_opt_state_shardings(
                self.opt_state,
                self.mesh,
                params=params,
                param_shardings=self._current_param_shardings(),
                min_size_to_shard=self.zero_min_size_to_shard,
            )
            # Committed placement: the 1/dp layout is established once here;
            # every jitted step after this reads/writes the local shard only.
            self.opt_state = jax.tree_util.tree_map(
                jax.device_put, self.opt_state, self.opt_state_shardings
            )
        if self.offload_to_host:
            from .parallel.host_offload import to_host

            self.opt_state = to_host(self.opt_state, self.mesh)
        self.acc_grads = None
        self._accumulated = 0

    def _current_param_shardings(self):
        """Param shardings from the bound model (preferred) or construction."""
        if self._model is not None and getattr(self._model, "param_shardings", None) is not None:
            return self._model.param_shardings
        return self.param_shardings

    # -- parity surface -------------------------------------------------
    @property
    def step_was_skipped(self) -> bool:
        """True if the last ``step()`` skipped (accumulating, or non-finite
        fp16 grads) (reference: optimizer.py:173). Reading this after a fused
        fp16 step forces a device sync on the finite flag."""
        if self._last_finite is not None:
            return not bool(jax.device_get(self._last_finite))
        return self._step_was_skipped

    @property
    def steps_applied(self) -> int:
        """Number of *applied* (finite) optimizer updates. Drains any pending
        fused-step finite flags (device sync) on read."""
        if self._pending_finite:
            flags = jax.device_get(self._pending_finite)
            self._steps_applied += int(sum(bool(f) for f in flags))
            self._pending_finite = []
        return self._steps_applied

    def zero_grad(self, set_to_none: bool = True):
        """Drop accumulated gradients (reference: optimizer.py:112 — no-op
        while accumulating)."""
        if self.gradient_state.sync_gradients:
            self.acc_grads = None
            self._accumulated = 0

    def accumulate_grads(self, grads):
        """Add a microbatch's gradients into the device-side accumulator."""
        if self.acc_grads is None:
            self.acc_grads = grads
        else:
            self.acc_grads = jax.tree_util.tree_map(jnp.add, self.acc_grads, grads)
        self._accumulated += 1

    def _build_apply(self):
        tx = self.tx
        has_scale = self.loss_scale is not None
        kwargs = self.scaler_kwargs

        def _apply(params, opt_state, grads, loss_scale, inv_scale):
            if has_scale:
                # inv_scale is 1/scale normally, or 1.0 when clip_grad_norm_
                # already unscaled the accumulated grads.
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * inv_scale).astype(g.dtype), grads
                )
                finite = grads_finite(grads)
                updates, new_opt_state = tx.update(grads, opt_state, params)
                import optax

                new_params = optax.apply_updates(params, updates)
                # Select: skip everything if non-finite.
                new_params = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o), new_params, params
                )
                new_opt_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o) if hasattr(n, "dtype") else n,
                    new_opt_state,
                    opt_state,
                )
                new_scale = update_loss_scale(loss_scale, finite, kwargs)
                return new_params, new_opt_state, new_scale, finite
            else:
                updates, new_opt_state = tx.update(grads, opt_state, params)
                import optax

                new_params = optax.apply_updates(params, updates)
                return new_params, new_opt_state, loss_scale, jnp.asarray(True)

        if self.opt_state_shardings is not None:
            # ZeRO: pin params and opt_state in/out. Without the explicit
            # params out-sharding GSPMD would propagate the moments' dp
            # sharding onto the updated params (breaking the donation alias
            # and leaving params partitioned); with it, the update lowers to
            # reduce-scatter(grads) -> 1/dp Adam -> all-gather(params).
            from .parallel.sharding import replicated_sharding

            p_sh = self._current_param_shardings()
            if p_sh is None:
                repl = replicated_sharding(self.mesh)
                p_sh = jax.tree_util.tree_map(lambda _: repl, self._model.params)
            o_sh = self.opt_state_shardings
            return jax.jit(
                _apply,
                donate_argnums=(0, 1, 2),
                in_shardings=(p_sh, o_sh, None, None, None),
                out_shardings=(p_sh, o_sh, None, None),
            )
        return jax.jit(_apply, donate_argnums=(0, 1, 2))

    def step(self, closure=None):
        """Apply accumulated gradients if in a sync step (reference:
        optimizer.py:138-172)."""
        if not self.gradient_state.sync_gradients:
            self._step_was_skipped = True
            return
        if self.acc_grads is None:
            self._step_was_skipped = True
            return
        if self._model is None:
            raise RuntimeError("Optimizer is not bound to a model; use Accelerator.prepare.")
        if self._apply_jit is None:
            self._apply_jit = self._build_apply()
        if self.loss_scale is not None:
            inv_scale = (
                jnp.asarray(1.0, jnp.float32)
                if self._grads_already_unscaled
                else 1.0 / self.loss_scale.scale
            )
        else:
            inv_scale = jnp.asarray(1.0, jnp.float32)
        if self.offload_to_host:
            # Stream the state HBM-ward only for the (FLOP-light) update; the
            # backward that produced acc_grads ran without it resident.
            from .parallel.host_offload import to_device, to_host

            opt_in = to_device(self.opt_state, self.mesh)
        else:
            opt_in = self.opt_state
        from .parallel.sharding import zero_step_compile_cache_guard

        with zero_step_compile_cache_guard(
            self.opt_state_shardings is not None and jax.default_backend() == "cpu"
        ):
            params, opt_state, new_scale, finite = self._apply_jit(
                self._model.params, opt_in, self.acc_grads, self.loss_scale, inv_scale
            )
        if self.offload_to_host:
            opt_state = to_host(opt_state, self.mesh)
        self._grads_already_unscaled = False
        self._model.params = params
        self.opt_state = opt_state
        self.loss_scale = new_scale
        applied = bool(finite) if self.loss_scale is not None else True
        self._step_was_skipped = not applied
        self._last_finite = None  # eager path: the flag above is authoritative
        if applied:
            self._steps_applied += 1
        self.acc_grads = None
        self._accumulated = 0

    # -- checkpoint surface ---------------------------------------------
    def state_dict(self):
        """Host-side snapshot of optimizer state (reference parity)."""
        sd = {"opt_state": self.opt_state, "steps_applied": self._steps_applied}
        if self.loss_scale is not None:
            sd["loss_scale"] = self.loss_scale
        return sd

    def load_state_dict(self, sd):
        """Restore a state_dict snapshot."""
        self.opt_state = sd["opt_state"]
        if self.offload_to_host:
            from .parallel.host_offload import to_host

            self.opt_state = to_host(self.opt_state, self.mesh)
        self._steps_applied = sd.get("steps_applied", 0)
        if "loss_scale" in sd and sd["loss_scale"] is not None:
            ls = sd["loss_scale"]
            self.loss_scale = LossScaleState(
                scale=jnp.asarray(ls[0]), growth_tracker=jnp.asarray(ls[1]), fin_steps=jnp.asarray(ls[2])
            )

    def __repr__(self):
        return f"AcceleratedOptimizer({self.tx.__class__.__name__}, accumulated={self._accumulated})"
