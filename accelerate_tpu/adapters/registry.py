"""AdapterBank: many LoRA adapters resident on device as one stacked tree.

S-LoRA/Punica-style multi-tenant serving: the bank holds ``max_adapters``
rank-padded adapters stacked on a leading axis (``a: [M, in, R]``,
``b: [M, R, out]``, ``scale: [M]``), so the engine's compiled forward can
gather any slot's adapter with a plain index — *membership is data*. Row 0
is reserved as the identity (all-zero) adapter for base-model requests;
its delta is exactly ``0.0``, so base requests through a bank-equipped
engine produce the same tokens as the bare engine.

The host side is a named registry with LRU residency. ``acquire`` pins a
named adapter into a row (loading/evicting via one pre-compiled
``dynamic_update_slice`` row write — the bank's shape never changes, so no
executable is ever recompiled); ``release`` unpins it when the request
retires. All bookkeeping is lock-protected: ``register``/lookups come from
caller threads while ``acquire``/``release`` run on the engine thread.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .lora import (
    LoRAConfig,
    adapter_module_paths,
    adapter_rank,
    pad_adapter,
    target_paths,
    _get_path,
    _set_path,
)


class UnknownAdapterError(LookupError):
    """Request names an adapter nobody registered (HTTP 404 at the gateway)."""


class AdapterBankFull(RuntimeError):
    """Every bank row is pinned by an in-flight request — retry later.

    Deliberately *not* an engine fault: the engine stays healthy and the
    request fails with a retryable, structured error (HTTP 503 +
    Retry-After at the gateway).
    """


class AdapterBank:
    """Fixed-shape device bank + host LRU registry of named adapters."""

    def __init__(self, params, *, config: Optional[LoRAConfig] = None,
                 max_adapters: int = 8, dtype=jnp.float32):
        if max_adapters < 2:
            raise ValueError(
                f"max_adapters must be >= 2 (row 0 is the reserved identity "
                f"adapter; got {max_adapters})")
        self.config = config or LoRAConfig()
        self.max_adapters = int(max_adapters)
        self.rank = int(self.config.rank)
        self._dtype = dtype
        self._lock = threading.Lock()

        # Stacked zero bank: one [M, ...] leaf per target-module leaf.
        self._paths = target_paths(params, self.config)
        stacks: dict = {}
        self._shapes: dict = {}
        M, R = self.max_adapters, self.rank
        for dotted in self._paths:
            kernel = _get_path(params, dotted)["kernel"]
            d_in, d_out = int(kernel.shape[0]), int(kernel.shape[1])
            self._shapes[dotted] = (d_in, d_out)
            _set_path(stacks, dotted, {
                "a": jnp.zeros((M, d_in, R), dtype),
                "b": jnp.zeros((M, R, d_out), dtype),
                "scale": jnp.zeros((M,), dtype),
            })
        self.stacks = stacks

        # Host registry / residency. Row 0 is permanently the identity.
        self._registered: dict = {}            # name -> padded host adapter
        self._rows: dict = {}                  # resident name -> row index
        self._row_of: list = [None] * M        # row index -> name (None = free)
        self._lru: OrderedDict = OrderedDict()  # resident names, LRU -> MRU
        self._pins: dict = {}                  # name -> in-flight pin count
        self.loads = 0
        self.evictions = 0

        def write_row(stacks, row, host):
            return jax.tree_util.tree_map(
                lambda s, u: jax.lax.dynamic_update_slice(
                    s, u.astype(s.dtype)[None], (row,) + (0,) * u.ndim),
                stacks, host)

        self._write = jax.jit(write_row)
        self._write_row_fn = write_row
        self._placed_mesh = None
        # Compile the (only) row-write program up front by re-writing the
        # identity into row 0 — later loads reuse this executable.
        self.stacks = self._write(self.stacks, jnp.int32(0), self._identity())

    def place(self, shardings) -> None:
        """Shard the bank across a serving slice (mesh-sliced engines).

        ``shardings`` is a NamedSharding pytree matching :attr:`stacks`
        (from ``SliceExec.bank_shardings``: each target's LoRA factors laid
        out like its base kernel — column-parallel targets shard ``b`` on
        ``d_out``, row-parallel ``a`` on ``d_in``; the row axis never
        splits). The stacks move onto the slice and the row-write program
        is re-jitted with matching in/out shardings, so later
        loads/evictions keep writing ONE ``dynamic_update_slice`` per leaf
        straight into the sharded layout — residency stays recompile-free.

        Engine-construction time only, and once per bank: a bank placed on
        one slice cannot serve another (each ``from_mesh`` slice engine
        builds its own via ``make_adapters``).
        """
        from jax.sharding import NamedSharding, PartitionSpec

        leaves = jax.tree_util.tree_leaves(
            shardings, is_leaf=lambda x: hasattr(x, "spec"))
        mesh = leaves[0].mesh
        with self._lock:
            if self._placed_mesh is not None and self._placed_mesh != mesh:
                raise ValueError(
                    "AdapterBank is already placed on another mesh slice; "
                    "each mesh-sliced engine needs its OWN bank (pass a "
                    "make_adapters factory to ReplicaSet.from_mesh)")
            self._placed_mesh = mesh
            replicated = NamedSharding(mesh, PartitionSpec())
            self.stacks = jax.tree_util.tree_map(
                lambda s, sh: jax.device_put(s, sh), self.stacks, shardings)
            self._write = jax.jit(
                self._write_row_fn,
                in_shardings=(shardings, replicated, replicated),
                out_shardings=shardings)
            self.stacks = self._write(self.stacks, jnp.int32(0),
                                      self._identity())

    # ------------------------------------------------------------------
    # host registry
    # ------------------------------------------------------------------

    def _identity(self):
        ident: dict = {}
        for dotted in self._paths:
            d_in, d_out = self._shapes[dotted]
            _set_path(ident, dotted, {
                "a": np.zeros((d_in, self.rank), np.float32),
                "b": np.zeros((self.rank, d_out), np.float32),
                "scale": np.zeros((), np.float32),
            })
        return ident

    @property
    def capacity(self) -> int:
        """Rows available to named adapters (row 0 is reserved)."""
        return self.max_adapters - 1

    def register(self, name: str, adapter, *, allow_update: bool = False) -> None:
        """Add a named adapter to the host registry (device load is lazy).

        The adapter may target any *subset* of the bank's modules and any
        rank <= the bank rank; missing modules become zero deltas and lower
        ranks are zero-padded, so heterogeneous tenants share one bank.
        """
        if not name or not isinstance(name, str):
            raise ValueError(f"adapter name must be a non-empty string (got {name!r})")
        r = adapter_rank(adapter)
        if r > self.rank:
            raise ValueError(
                f"adapter {name!r} has rank {r} > bank rank {self.rank}")
        padded = pad_adapter(adapter, self.rank)
        host = self._identity()
        for dotted in adapter_module_paths(padded):
            if dotted not in self._shapes:
                raise ValueError(
                    f"adapter {name!r} targets {dotted!r}, which is not a "
                    f"bank target (bank targets: {self._paths})")
            mod = _get_path(padded, dotted)
            d_in, d_out = self._shapes[dotted]
            got = (tuple(np.shape(mod["a"])), tuple(np.shape(mod["b"])))
            want = ((d_in, self.rank), (self.rank, d_out))
            if got != want:
                raise ValueError(
                    f"adapter {name!r} module {dotted!r} has shapes {got}, "
                    f"expected {want}")
            _set_path(host, dotted, {
                "a": np.asarray(jax.device_get(mod["a"]), np.float32),
                "b": np.asarray(jax.device_get(mod["b"]), np.float32),
                "scale": np.asarray(jax.device_get(mod["scale"]), np.float32),
            })
        with self._lock:
            if name in self._registered and not allow_update:
                raise ValueError(
                    f"adapter {name!r} is already registered "
                    "(pass allow_update=True to replace it)")
            if self._pins.get(name, 0) > 0:
                raise RuntimeError(
                    f"adapter {name!r} has in-flight requests; cannot replace")
            # Drop any stale residency so the next acquire reloads new bytes.
            row = self._rows.pop(name, None)
            if row is not None:
                self._row_of[row] = None
                self._lru.pop(name, None)
            self._registered[name] = host

    def unregister(self, name: str) -> None:
        with self._lock:
            if name not in self._registered:
                raise UnknownAdapterError(name)
            if self._pins.get(name, 0) > 0:
                raise RuntimeError(
                    f"adapter {name!r} has in-flight requests; cannot unregister")
            del self._registered[name]
            row = self._rows.pop(name, None)
            if row is not None:
                self._row_of[row] = None
                self._lru.pop(name, None)

    def names(self) -> list:
        with self._lock:
            return sorted(self._registered)

    def resident(self, name: str) -> bool:
        with self._lock:
            return name in self._rows

    def check_known(self, name: str) -> None:
        with self._lock:
            if name not in self._registered:
                known = sorted(self._registered)
                raise UnknownAdapterError(
                    f"unknown adapter {name!r} (registered: {known})")

    # ------------------------------------------------------------------
    # residency (engine thread)
    # ------------------------------------------------------------------

    def acquire(self, name: str):
        """Pin ``name`` into a bank row; load (and maybe evict) if absent.

        Returns ``(row, hit, evicted_name_or_None)``. Raises
        :class:`UnknownAdapterError` for unregistered names and
        :class:`AdapterBankFull` when every row is pinned by in-flight work.
        """
        with self._lock:
            if name not in self._registered:
                raise UnknownAdapterError(
                    f"unknown adapter {name!r} (registered: {sorted(self._registered)})")
            if name in self._rows:
                self._lru.move_to_end(name)
                self._pins[name] = self._pins.get(name, 0) + 1
                return self._rows[name], True, None

            evicted = None
            row = next(
                (i for i in range(1, self.max_adapters) if self._row_of[i] is None),
                None)
            if row is None:
                for cand in self._lru:  # LRU -> MRU
                    if self._pins.get(cand, 0) == 0:
                        evicted = cand
                        break
                if evicted is None:
                    raise AdapterBankFull(
                        f"all {self.capacity} adapter rows are pinned by "
                        f"in-flight requests; retry adapter {name!r} later")
                row = self._rows.pop(evicted)
                self._lru.pop(evicted)
                self._row_of[row] = None
                self.evictions += 1

            # Row write runs on the engine thread only; reassigning
            # self.stacks functionally keeps compiled callers coherent.
            self.stacks = self._write(
                self.stacks, jnp.int32(row), self._registered[name])
            self._rows[name] = row
            self._row_of[row] = name
            self._lru[name] = None
            self._pins[name] = self._pins.get(name, 0) + 1
            self.loads += 1
            return row, False, evicted

    def release(self, name: str) -> None:
        with self._lock:
            n = self._pins.get(name, 0)
            if n <= 1:
                self._pins.pop(name, None)
            else:
                self._pins[name] = n - 1

    def counters(self) -> dict:
        with self._lock:
            return {
                "registered": len(self._registered),
                "resident": len(self._rows),
                "capacity": self.capacity,
                "loads": self.loads,
                "evictions": self.evictions,
            }
