"""Serving-side base-weight quantization with an exact LoRA path.

The serving engine's ``weights_dtype="int8"`` mode stores BASE weights as
:class:`~accelerate_tpu.utils.quantization.QuantizedTensor` pytree leaves
(per-output-channel symmetric int8, the TPU weight-only-quant layout) and
dequantizes them at the top of each compiled program — XLA fuses the
``convert(int8) * scale`` into the consuming dot, so weights at rest in
HBM stay integer. The LoRA low-rank path is deliberately NOT quantized:
adapter factors live full precision in the :class:`~.registry.AdapterBank`
(identity row 0 included), so multi-tenant adapters apply exactly on top
of the quantized base — per-tenant deltas never accumulate quantization
error of their own.

This module is the thin serving-facing prepare path over
:mod:`accelerate_tpu.utils.quantization`:

* :func:`quantize_base_weights` — params pytree → pytree with eligible
  kernel leaves replaced by ``QuantizedTensor`` nodes.
* :func:`shardings_for_quantized` — map a slice's full-precision TP
  shardings onto a quantized tree: the int ``q`` takes the kernel's
  Megatron spec, its ``scale`` keeps a spec axis only where the scale dim
  equals the kernel dim (size-1 amax dims replicate) — so quantized
  serving composes with ``tp=`` slices with zero changes to the sharding
  rules themselves.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..utils.quantization import (
    QuantizationConfig,
    QuantizedTensor,
    _is_quantized,
    dequantize_params,
    quantize_params,
    quantized_nbytes,
)

__all__ = [
    "quantize_base_weights",
    "shardings_for_quantized",
    "dequantize_params",
    "quantized_nbytes",
]

#: leaves below this size stay full precision (norms, biases, tiny heads)
#: — small enough that the serving test models exercise the real path.
SERVING_MIN_WEIGHT_SIZE = 256

#: path regexes kept full precision for output quality: the unembedding
#: head (reference keeps lm_head fp) and the token embedding table, whose
#: per-column scale poorly fits a vocab-long axis.
SERVING_SKIP_MODULES = ("lm_head", "embed")


def quantize_base_weights(params, *, min_weight_size: int | None = None,
                          skip_modules=None):
    """Quantize a serving model's base params to per-channel int8.

    Returns a new pytree where each eligible kernel leaf (ndim >= 2, size
    >= ``min_weight_size``, path not matching ``skip_modules``) is a
    :class:`QuantizedTensor`; everything else is untouched. Idempotent on
    already-quantized leaves. LoRA adapter factors never pass through
    here — the bank holds them full precision by construction.
    """
    cfg = QuantizationConfig(
        load_in_8bit=True,
        min_weight_size=(SERVING_MIN_WEIGHT_SIZE if min_weight_size is None
                         else int(min_weight_size)),
        skip_modules=list(skip_modules if skip_modules is not None
                          else SERVING_SKIP_MODULES),
    )
    return quantize_params(params, cfg)


def shardings_for_quantized(exec_, qparams):
    """TP shardings for a quantized param tree under one serving slice.

    Derives the slice's full-precision shardings from the LOGICAL shapes
    (``QuantizedTensor.shape`` is the kernel's shape, so the Megatron
    path-regex rules apply unchanged), then rebuilds the tree with a
    ``QuantizedTensor`` of shardings at each quantized position: ``q``
    takes the kernel's spec verbatim; ``scale`` keeps an axis name only
    where its dim matches the kernel's (the amax-reduced size-1 dim
    replicates). The treedefs match (same aux data), so ``device_put``,
    ``jit in_shardings``, and the engine's place path all accept the
    result exactly like a plain sharding pytree.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    shapes = jax.tree_util.tree_map(
        lambda l: (jax.ShapeDtypeStruct(tuple(l.shape), jnp.float32)
                   if _is_quantized(l) else l),
        qparams, is_leaf=_is_quantized)
    fp_sh = exec_.param_shardings(shapes)

    def _pair(leaf, sh):
        if not _is_quantized(leaf):
            return sh
        if leaf.bits != 8:
            raise NotImplementedError(
                "serving weight quantization shards int8 leaves only "
                f"(got int{leaf.bits})")
        spec = list(sh.spec) + [None] * (leaf.ndim - len(sh.spec))
        sspec = [ax if (ax is not None
                        and leaf.scale.shape[i] == leaf.q.shape[i])
                 else None
                 for i, ax in enumerate(spec)]
        scale_sh = NamedSharding(sh.mesh, PartitionSpec(*sspec))
        return QuantizedTensor(sh, scale_sh, leaf.bits, leaf.block_size)

    return jax.tree_util.tree_map(_pair, qparams, fp_sh,
                                  is_leaf=_is_quantized)
