"""Multi-tenant LoRA adapters: train, hot-load, and serve many adapters
over one base model with zero recompiles.

Core (``adapters.lora``): config/init/merge plus the pure low-rank
application path. Serving (``adapters.registry``): the stacked device
:class:`AdapterBank` with host-side named LRU residency. Checkpoint
round-trips live in :mod:`accelerate_tpu.checkpointing`
(``save_adapter``/``load_adapter``) and are re-exported here.
"""

from ..checkpointing import load_adapter, save_adapter
from .lora import (
    DEFAULT_TARGET_MODULES,
    LoRAConfig,
    LoRATrainState,
    adapter_rank,
    count_lora_params,
    init_lora_params,
    lora_delta,
    merge_adapter,
    pad_adapter,
    prepare_lora,
    target_paths,
)
from .quantize import quantize_base_weights, shardings_for_quantized
from .registry import AdapterBank, AdapterBankFull, UnknownAdapterError

__all__ = [
    "DEFAULT_TARGET_MODULES",
    "LoRAConfig",
    "LoRATrainState",
    "AdapterBank",
    "AdapterBankFull",
    "UnknownAdapterError",
    "adapter_rank",
    "count_lora_params",
    "init_lora_params",
    "load_adapter",
    "lora_delta",
    "merge_adapter",
    "pad_adapter",
    "prepare_lora",
    "quantize_base_weights",
    "save_adapter",
    "shardings_for_quantized",
    "target_paths",
]
