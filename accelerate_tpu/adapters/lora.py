"""LoRA core: config, init, merge, and the pure low-rank application path.

One adapter is a *pytree mirroring the base params*: every targeted
projection module (a dict holding a 2-D ``kernel``) is replaced by
``{"a": [in, r], "b": [r, out], "scale": []}``. That uniform shape is what
lets the serving side stack many adapters into one bank array per leaf and
gather a slot's adapter inside a compiled forward — the low-rank delta is
always computed as ``((x @ a) @ b) * scale`` and *added* to the base
projection output; the merged matrix ``W + a @ b * scale`` is only ever
materialized offline by :func:`merge_adapter`.

Training uses the same tree: :func:`prepare_lora` splits params into a
frozen base and a trainable adapter plus a boolean mask shaped like the
combined tree for ``optax.masked`` — the base never sees an optimizer
update, so adapter checkpoints stay a few MB regardless of model size.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

#: Llama-family projection names; the default target set covers attention
#: and MLP, matching the common "all-linear" LoRA recipe.
DEFAULT_TARGET_MODULES = (
    "q_proj", "k_proj", "v_proj", "o_proj",
    "gate_proj", "up_proj", "down_proj",
)

#: Leaf names of one adapter module, in stacking order.
ADAPTER_LEAVES = ("a", "b", "scale")


@dataclasses.dataclass(frozen=True)
class LoRAConfig:
    """Hyperparameters + which modules to adapt.

    ``target_modules`` entries are fnmatch patterns. A pattern containing a
    ``.`` or ``/`` is matched against the full dot-joined module path
    (``model.layers_0.self_attn.q_proj``); otherwise it matches the module's
    own name (``q_proj``), the usual shorthand.
    """

    rank: int = 8
    alpha: float = 16.0
    dropout: float = 0.0
    target_modules: Sequence[str] = DEFAULT_TARGET_MODULES

    def __post_init__(self):
        if self.rank < 1:
            raise ValueError(f"LoRA rank must be >= 1 (got {self.rank})")
        if not 0.0 <= self.dropout < 1.0:
            raise ValueError(f"dropout must be in [0, 1) (got {self.dropout})")
        if not self.target_modules:
            raise ValueError("target_modules must not be empty")
        object.__setattr__(self, "target_modules", tuple(self.target_modules))

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _matches(path: tuple, patterns: Sequence[str]) -> bool:
    dotted = ".".join(path)
    name = path[-1]
    for pat in patterns:
        if "." in pat or "/" in pat:
            if fnmatch.fnmatch(dotted, pat.replace("/", ".")):
                return True
        elif fnmatch.fnmatch(name, pat):
            return True
    return False


def target_paths(params, config: LoRAConfig) -> list:
    """Dot-paths of the modules a :class:`LoRAConfig` adapts.

    A target is a dict with a 2-D ``kernel`` whose path matches one of
    ``config.target_modules``. Embeddings, norms, and higher-rank kernels
    (convs) are never matched.
    """
    found = []

    def walk(node, path):
        if not isinstance(node, dict):
            return
        kernel = node.get("kernel")
        if (
            path
            and hasattr(kernel, "ndim")
            and kernel.ndim == 2
            and _matches(path, config.target_modules)
        ):
            found.append(".".join(path))
            return
        for k in sorted(node):
            walk(node[k], path + (k,))

    walk(params, ())
    if not found:
        raise ValueError(
            f"target_modules {tuple(config.target_modules)!r} matched nothing "
            "in the params pytree"
        )
    return found


def _get_path(tree, dotted: str):
    node = tree
    for part in dotted.split("."):
        node = node[part]
    return node


def _set_path(tree: dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    node = tree
    for part in parts[:-1]:
        node = node.setdefault(part, {})
    node[parts[-1]] = value


def init_lora_params(rng, params, config: LoRAConfig, dtype=jnp.float32):
    """Fresh adapter for ``params``: ``a`` ~ N(0, 1/r), ``b`` = 0.

    ``b = 0`` makes the initial delta exactly zero — training starts from
    the base model's function, the standard LoRA init.
    """
    paths = target_paths(params, config)
    adapter: dict = {}
    keys = jax.random.split(rng, len(paths))
    for key, dotted in zip(keys, paths):
        kernel = _get_path(params, dotted)["kernel"]
        d_in, d_out = int(kernel.shape[0]), int(kernel.shape[1])
        _set_path(adapter, dotted, {
            "a": jax.random.normal(key, (d_in, config.rank), dtype) / config.rank,
            "b": jnp.zeros((config.rank, d_out), dtype),
            "scale": jnp.asarray(config.scale, dtype),
        })
    return adapter


def is_adapter_module(node) -> bool:
    return isinstance(node, dict) and set(node) == set(ADAPTER_LEAVES)


def adapter_module_paths(adapter) -> list:
    """Dot-paths of every ``{"a","b","scale"}`` module in an adapter tree."""
    found = []

    def walk(node, path):
        if is_adapter_module(node):
            found.append(".".join(path))
        elif isinstance(node, dict):
            for k in sorted(node):
                walk(node[k], path + (k,))

    walk(adapter, ())
    return found


def adapter_rank(adapter) -> int:
    ranks = [
        _get_path(adapter, p)["a"].shape[-1] for p in adapter_module_paths(adapter)
    ]
    if not ranks:
        raise ValueError("not an adapter tree: no {'a','b','scale'} modules found")
    return int(max(ranks))


def pad_adapter(adapter, rank: int):
    """Zero-pad every module to ``rank`` (a: extra columns, b: extra rows).

    Padding with zeros leaves ``a @ b`` unchanged, so rank-4 and rank-8
    adapters can share one rank-8 bank row layout.
    """

    def pad(node):
        r = node["a"].shape[-1]
        if r > rank:
            raise ValueError(f"adapter rank {r} exceeds bank rank {rank}")
        if r == rank:
            return dict(node)
        a = jnp.pad(node["a"], ((0, 0), (0, rank - r)))
        b = jnp.pad(node["b"], ((0, rank - r), (0, 0)))
        return {"a": a, "b": b, "scale": node["scale"]}

    out: dict = {}
    for dotted in adapter_module_paths(adapter):
        _set_path(out, dotted, pad(_get_path(adapter, dotted)))
    return out


def lora_delta(x, module, dtype=None):
    """Low-rank delta ``((x @ a) @ b) * scale`` — never forms ``a @ b``."""
    dtype = dtype or x.dtype
    a = module["a"].astype(dtype)
    b = module["b"].astype(dtype)
    return ((x @ a) @ b) * module["scale"].astype(dtype)


def merge_adapter(params, adapter):
    """Fold an adapter into full weights: ``kernel += a @ b * scale``.

    Offline-only path (single-tenant export, exactness references). The
    batched serving path never calls this — it applies the low-rank delta
    per token instead.
    """
    merged = jax.tree_util.tree_map(lambda x: x, params)  # structural copy
    for dotted in adapter_module_paths(adapter):
        mod = _get_path(adapter, dotted)
        target = _get_path(merged, dotted)
        kernel = target["kernel"]
        delta = (mod["a"] @ mod["b"]) * mod["scale"]
        target["kernel"] = (kernel.astype(jnp.float32) + delta.astype(jnp.float32)).astype(kernel.dtype)
    return merged


@dataclasses.dataclass
class LoRATrainState:
    """Frozen-base / trainable-adapter split from :func:`prepare_lora`.

    ``train_params()`` is what you differentiate and hand to the optimizer;
    ``param_mask`` (True = trainable) has the same structure. Wrap your
    optimizer with :meth:`wrap_optimizer` — a bare ``optax.masked(tx,
    mask)`` is NOT enough, because masked passes the unmasked leaves'
    gradients through unmodified instead of zeroing them.
    """

    base_params: dict
    adapter: dict
    param_mask: dict
    config: LoRAConfig

    def train_params(self) -> dict:
        return {"base": self.base_params, "lora": self.adapter}

    def wrap_optimizer(self, tx):
        """``tx`` on the trainable leaves, hard zero everywhere else —
        the frozen base (and the scale hyperparameter leaves) come out of
        every update bit-identical."""
        import optax

        frozen = jax.tree_util.tree_map(lambda t: not t, self.param_mask)
        return optax.chain(optax.masked(tx, self.param_mask),
                           optax.masked(optax.set_to_zero(), frozen))


def prepare_lora(model, params, config: LoRAConfig, rng=None) -> LoRATrainState:
    """Split ``params`` into a frozen base + fresh trainable adapter.

    ``model`` is accepted for API symmetry with the training stack (it is
    only used to validate that the adapter's targets exist); apply the
    adapter at call time via the model's ``lora=`` hook, e.g.::

        ts = prepare_lora(model, params, LoRAConfig(rank=8))
        tx = ts.wrap_optimizer(optax.adamw(1e-4))

        def loss_fn(train):
            logits = model.apply({"params": train["base"]}, batch,
                                 lora=train["lora"])
            ...
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    adapter = init_lora_params(rng, params, config)

    def leaf_mask(tree, value, scale_value):
        def walk(node, path):
            if not isinstance(node, dict):
                return scale_value if path and path[-1] == "scale" else value
            return {k: walk(v, path + (k,)) for k, v in node.items()}

        return walk(tree, ())

    # scale is a hyperparameter leaf, not a weight — keep it frozen too.
    mask = {
        "base": leaf_mask(params, False, False),
        "lora": leaf_mask(adapter, True, False),
    }
    return LoRATrainState(base_params=params, adapter=adapter,
                          param_mask=mask, config=config)


def count_lora_params(abstract_params, config: LoRAConfig) -> tuple:
    """(trainable parameter count, fp32 checkpoint bytes) for an adapter.

    Works on abstract trees (``jax.eval_shape`` output) — used by the
    ``estimate-memory --lora-rank`` CLI without materializing weights.
    """
    n = 0
    for dotted in target_paths(abstract_params, config):
        kernel = _get_path(abstract_params, dotted)["kernel"]
        d_in, d_out = int(kernel.shape[0]), int(kernel.shape[1])
        n += d_in * config.rank + config.rank * d_out
    return n, n * 4


def adapter_spec(adapter) -> dict:
    """Shape spec used to validate bank registration and checkpoints."""
    spec = {}
    for dotted in adapter_module_paths(adapter):
        mod = _get_path(adapter, dotted)
        spec[dotted] = {k: tuple(np.shape(mod[k])) for k in ADAPTER_LEAVES}
    return spec
