"""Turn raw per-stream results into the open-loop serving report.

The report is the contract between the harness and everything that
consumes it — ``bench.py`` (``extra.serving.open_loop``), the
``accelerate-tpu loadtest`` CLI, and the overload-conformance tests —
so it is plain JSON-serialisable data with explicit conventions:

* Latency percentiles are computed over **offered** streams, not
  completed ones: a stream the saturated server refused (or never
  finished) has unbounded TTFT. Unbounded values surface two ways —
  ``None`` in the honest percentiles plus an ``unbounded_fraction``,
  and finite ``*_clamped`` twins (unbounded replaced by ``clamp_s``)
  for guard ratios and trajectory payloads that need numbers.
* Every stream lands in exactly ONE outcome bucket, so
  ``sum(outcomes.values()) == offered.n`` is the token-accounting
  balance the conformance tests assert.
"""

from __future__ import annotations

import math
from typing import Optional

__all__ = ["percentile", "build_report"]

#: non-2xx codes that are *structured* refusals — anything else under
#: overload is a conformance failure.
_STRUCTURED = (408, 429, 503)
#: of those, the ones that must carry a bounded Retry-After.
_NEEDS_RETRY_AFTER = (429, 503)


def percentile(values, q: float) -> Optional[float]:
    """Nearest-rank percentile (q in [0, 100]) tolerant of ``inf``
    entries; returns None for an empty list and ``inf`` stays ``inf``
    (callers decide how to serialise it)."""
    vals = sorted(values)
    if not vals:
        return None
    if not 0 <= q <= 100:
        raise ValueError("q must be in [0, 100]")
    rank = max(1, math.ceil(q / 100.0 * len(vals)))
    return float(vals[rank - 1])


def _pcts(values, clamp_s: Optional[float]) -> dict:
    """{p50, p99, p999, mean} twice: honest (None for unbounded) and
    clamped (inf -> clamp_s, always finite when clamp_s given)."""
    def scrub(v):
        return None if v is None or math.isinf(v) else v

    out = {}
    for name, q in (("p50", 50.0), ("p99", 99.0), ("p999", 99.9)):
        out[name] = scrub(percentile(values, q))
    finite = [v for v in values if not math.isinf(v)]
    out["mean"] = (sum(finite) / len(finite)) if finite else None
    out["unbounded_fraction"] = (
        (len(values) - len(finite)) / len(values) if values else 0.0)
    if clamp_s is not None:
        clamped = [min(v, clamp_s) for v in values]
        for name, q in (("p50", 50.0), ("p99", 99.0), ("p999", 99.9)):
            out[f"{name}_clamped"] = percentile(clamped, q)
    return out


def _bucket(r) -> str:
    """The one outcome bucket a stream belongs to (precedence order)."""
    if r.code is None:
        return "connect_error"
    if not 200 <= r.code < 300:
        return f"http_{r.code}"
    if r.aborted:
        return "aborted"
    if r.truncated:
        return "truncated_sse"
    if r.done is None:
        return "no_summary"  # JSON (non-stream) body missing — a bug
    status = r.done.get("status")
    return "completed" if status == "completed" else f"stream_{status}"


def build_report(run: dict, schedule, profile=None, *,
                 slo_ttft_s: Optional[float] = None,
                 clamp_s: Optional[float] = None,
                 server_metrics: Optional[dict] = None) -> dict:
    """Build the JSON report from a :func:`~.generator.run_open_loop`
    result. ``slo_ttft_s`` defines goodput (completions whose TTFT met
    the SLO, per second of wall time); ``clamp_s`` bounds the clamped
    percentile twins (defaults to the run's wall time)."""
    results = run["results"]
    n = len(results)
    if clamp_s is None:
        clamp_s = run.get("wall_s")
    outcomes: dict = {}
    for r in results:
        b = _bucket(r)
        outcomes[b] = outcomes.get(b, 0) + 1
    completed = [r for r in results if r.completed]

    # -- latency over OFFERED streams -------------------------------------
    inf = float("inf")
    ttfts = [r.ttft_s if r.ttft_s is not None else inf for r in results]
    itls: list = []
    for r in results:
        itls.extend(r.token_gaps_s)

    # -- goodput -----------------------------------------------------------
    def met_slo(r) -> bool:
        if slo_ttft_s is None:
            return True
        return r.ttft_s is not None and r.ttft_s <= slo_ttft_s

    good = sum(1 for r in completed if met_slo(r))
    wall = float(run.get("wall_s") or 0.0) or None

    # -- conformance -------------------------------------------------------
    non2xx = [r for r in results
              if r.code is not None and not 200 <= r.code < 300]
    unstructured = [r for r in non2xx if r.code not in _STRUCTURED]
    missing_retry = [r for r in non2xx
                     if r.code in _NEEDS_RETRY_AFTER
                     and (r.retry_after_s is None or r.retry_after_s < 0)]
    retry_afters = [r.retry_after_s for r in non2xx
                    if r.retry_after_s is not None and r.retry_after_s >= 0]
    # Token accounting: the gateway's final summary repeats the full
    # token list, so streamed-vs-summary mismatch means a duplicated or
    # lost SSE token event.
    token_mismatches = sum(
        1 for r in completed
        if r.done.get("tokens") is not None
        and r.tokens != [int(t) for t in r.done["tokens"]])

    # -- per-priority-class breakdown --------------------------------------
    # Keyed on the class each request DECLARED (the body's "priority";
    # requests without one land under "_none") — the legibility layer for
    # SLO claims: one run shows interactive's clamped tail next to
    # batch's, over offered streams per class like the headline numbers.
    def _class_of(r) -> str:
        body = r.request or {}
        return body.get("priority") or "_none"

    per_priority: dict = {}
    for r in results:
        per_priority.setdefault(_class_of(r), []).append(r)
    priority_report = {}
    for cls in sorted(per_priority):
        rs = per_priority[cls]
        cls_completed = [r for r in rs if r.completed]
        cls_good = sum(1 for r in cls_completed if met_slo(r))
        cls_ttfts = [r.ttft_s if r.ttft_s is not None else inf for r in rs]
        cls_itls: list = []
        for r in rs:
            cls_itls.extend(r.token_gaps_s)
        priority_report[cls] = {
            "offered": len(rs),
            "completed": len(cls_completed),
            "within_slo": cls_good,
            "goodput_rps": (cls_good / wall) if wall else None,
            "ttft_s": _pcts(cls_ttfts, clamp_s),
            "itl_s": _pcts(cls_itls, clamp_s),
        }

    report = {
        "offered": dict(schedule.describe(),
                        **({"profile": profile.describe()}
                           if profile is not None else {})),
        "run": {
            "wall_s": run.get("wall_s"),
            "process_cpu_s": run.get("process_cpu_s"),
            "host_cpu_s_per_stream": (
                run["process_cpu_s"] / n
                if run.get("process_cpu_s") is not None and n else None),
        },
        "outcomes": outcomes,
        "counters_balance": sum(outcomes.values()) == n,
        "goodput": {
            "slo_ttft_s": slo_ttft_s,
            "completed": len(completed),
            "within_slo": good,
            "goodput_rps": (good / wall) if wall else None,
        },
        "ttft_s": _pcts(ttfts, clamp_s),
        "itl_s": _pcts(itls, clamp_s),
        "per_priority": priority_report,
        "conformance": {
            "non_2xx": len(non2xx),
            "unstructured_non_2xx": len(unstructured),
            "missing_retry_after": len(missing_retry),
            "max_retry_after_s": max(retry_afters, default=None),
            "truncated_sse": outcomes.get("truncated_sse", 0),
            "token_mismatches": token_mismatches,
            "heartbeats": sum(r.heartbeats for r in results),
        },
    }
    if server_metrics:
        report["server_metrics"] = dict(server_metrics)
    return report
