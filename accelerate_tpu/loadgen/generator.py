"""Arrival schedules, traffic profiles, and the asyncio open-loop driver.

Everything here is seeded and deterministic given the seed: a schedule
is a *plan* (arrival offsets and request shapes fixed up front), and
``run_open_loop`` executes the plan against a live gateway from one
event loop, timestamping every stream against its scheduled arrival.
The driver speaks raw HTTP/1.1 over :func:`asyncio.open_connection` —
no client library, same stdlib-only rule as the gateway itself.
"""

from __future__ import annotations

import asyncio
import json
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

__all__ = ["ArrivalSchedule", "TrafficProfile", "StreamResult",
           "run_open_loop", "fetch_gateway_metrics"]


class ArrivalSchedule:
    """Seeded open-loop arrival plan: ``n`` streams, inter-arrival times
    drawn from a heavy-tailed (or uniform) distribution with a target
    mean, cumulated into arrival offsets starting at zero.

    The offered rate is a property of this object computed before any
    request is sent — ``run_open_loop`` dispatches on this clock no
    matter how the server is doing, which is what makes the load
    open-loop. Heavy tails matter: Poisson-ish smooth arrivals hide the
    burst behaviour that actually collapses queues, so the default is
    lognormal with a fat sigma, and ``dist="pareto"`` goes fatter.

    Args:
      n: number of streams.
      mean_interarrival_s: target mean gap between consecutive arrivals
        (``1 / offered_rps`` to first order).
      dist: ``"lognormal"`` (default), ``"pareto"``, or ``"uniform"``.
      sigma: lognormal log-space sigma (burstiness; 0 → near-constant).
      alpha: Pareto tail index (must be > 1 so the mean exists; closer
        to 1 → heavier tail).
      seed: RNG seed; the same seed always yields the same schedule.
    """

    DISTS = ("lognormal", "pareto", "uniform")

    def __init__(self, n: int, mean_interarrival_s: float, *,
                 dist: str = "lognormal", sigma: float = 1.0,
                 alpha: float = 1.5, seed: int = 0):
        if n < 1:
            raise ValueError("n must be >= 1")
        if mean_interarrival_s <= 0:
            raise ValueError("mean_interarrival_s must be > 0")
        if dist not in self.DISTS:
            raise ValueError(f"dist must be one of {self.DISTS} "
                             f"(got {dist!r})")
        if alpha <= 1:
            raise ValueError("alpha must be > 1 (finite-mean Pareto)")
        self.n = int(n)
        self.mean_interarrival_s = float(mean_interarrival_s)
        self.dist = dist
        self.sigma = float(sigma)
        self.alpha = float(alpha)
        self.seed = int(seed)
        rng = np.random.default_rng(seed)
        m = self.mean_interarrival_s
        if dist == "lognormal":
            # mean of LogNormal(mu, sigma) is exp(mu + sigma^2/2);
            # solve mu for the requested mean.
            mu = np.log(m) - self.sigma ** 2 / 2.0
            gaps = rng.lognormal(mu, self.sigma, size=n)
        elif dist == "pareto":
            # Lomax+1 scaled so the mean is alpha*xm/(alpha-1) == m.
            xm = m * (self.alpha - 1.0) / self.alpha
            gaps = (rng.pareto(self.alpha, size=n) + 1.0) * xm
        else:
            gaps = rng.uniform(0.0, 2.0 * m, size=n)
        gaps[0] = 0.0  # first arrival defines t=0
        self._offsets = np.cumsum(gaps)

    def offsets(self) -> np.ndarray:
        """Arrival offsets in seconds from run start, ascending,
        ``offsets()[0] == 0``."""
        return self._offsets.copy()

    @property
    def span_s(self) -> float:
        """Seconds between the first and last scheduled arrival."""
        return float(self._offsets[-1] - self._offsets[0])

    @property
    def offered_rps(self) -> float:
        """Offered arrival rate over the schedule span (n-1 gaps)."""
        if self.n == 1 or self.span_s == 0:
            return float("inf") if self.n > 1 else 0.0
        return (self.n - 1) / self.span_s

    def describe(self) -> dict:
        return {
            "n": self.n,
            "dist": self.dist,
            "mean_interarrival_s": self.mean_interarrival_s,
            "sigma": self.sigma,
            "alpha": self.alpha,
            "seed": self.seed,
            "span_s": self.span_s,
            "offered_rps": self.offered_rps,
        }


class TrafficProfile:
    """Seeded per-stream request shapes: heavy-tailed prompt/output
    lengths plus a mixed adapter / sampling-seed / priority blend.

    Lengths are lognormal around a median (the natural heavy-tail
    parameterisation: half the requests are short, a tail is very
    long), clipped into ``[min, max]`` so the engine's ``max_len``
    budget is respected by construction. ``adapters`` is a weighted mix
    where ``None`` means the base model; ``priorities`` ride in the
    request payload and the gateway carries them end to end into the
    engine's per-priority metrics series (measurement only — the
    baseline the SLO-control roadmap item will schedule on).
    """

    def __init__(self, *, prompt_len_median: int = 32,
                 prompt_len_sigma: float = 0.6,
                 prompt_len_min: int = 1, prompt_len_max: int = 128,
                 out_tokens_median: int = 16,
                 out_tokens_sigma: float = 0.6,
                 out_tokens_min: int = 1, out_tokens_max: int = 64,
                 adapters=((None, 1.0),),
                 sampled_fraction: float = 0.5,
                 priorities=(("interactive", 0.8), ("batch", 0.2)),
                 timeout_s: Optional[float] = None,
                 seed: int = 0):
        if prompt_len_min < 1 or prompt_len_max < prompt_len_min:
            raise ValueError("need 1 <= prompt_len_min <= prompt_len_max")
        if out_tokens_min < 1 or out_tokens_max < out_tokens_min:
            raise ValueError("need 1 <= out_tokens_min <= out_tokens_max")
        if not 0.0 <= sampled_fraction <= 1.0:
            raise ValueError("sampled_fraction must be in [0, 1]")
        self.prompt_len_median = int(prompt_len_median)
        self.prompt_len_sigma = float(prompt_len_sigma)
        self.prompt_len_min = int(prompt_len_min)
        self.prompt_len_max = int(prompt_len_max)
        self.out_tokens_median = int(out_tokens_median)
        self.out_tokens_sigma = float(out_tokens_sigma)
        self.out_tokens_min = int(out_tokens_min)
        self.out_tokens_max = int(out_tokens_max)
        self.adapters = tuple(adapters)
        self.sampled_fraction = float(sampled_fraction)
        self.priorities = tuple(priorities)
        self.timeout_s = timeout_s
        self.seed = int(seed)
        self._rng = np.random.default_rng(seed)

    def _weighted(self, choices):
        names = [c[0] for c in choices]
        w = np.asarray([c[1] for c in choices], float)
        return names[int(self._rng.choice(len(names), p=w / w.sum()))]

    def _length(self, median, sigma, lo, hi) -> int:
        # median of LogNormal(mu, sigma) is exp(mu).
        v = self._rng.lognormal(np.log(median), sigma)
        return int(np.clip(round(v), lo, hi))

    def sample(self, vocab_size: int = 256) -> dict:
        """One request body (JSON-ready dict) for ``POST
        /v1/completions``; ``stream`` is set by the driver."""
        plen = self._length(self.prompt_len_median, self.prompt_len_sigma,
                            self.prompt_len_min, self.prompt_len_max)
        body = {
            "prompt": self._rng.integers(
                0, vocab_size, size=plen).tolist(),
            "max_new_tokens": self._length(
                self.out_tokens_median, self.out_tokens_sigma,
                self.out_tokens_min, self.out_tokens_max),
            "ignore_eos": True,
            "priority": self._weighted(self.priorities),
        }
        adapter = self._weighted(self.adapters)
        if adapter is not None:
            body["adapter"] = adapter
        if float(self._rng.random()) < self.sampled_fraction:
            body["seed"] = int(self._rng.integers(0, 2 ** 31 - 1))
        if self.timeout_s is not None:
            body["timeout"] = self.timeout_s
        return body

    def describe(self) -> dict:
        return {
            "prompt_len": [self.prompt_len_median, self.prompt_len_sigma,
                           self.prompt_len_min, self.prompt_len_max],
            "out_tokens": [self.out_tokens_median, self.out_tokens_sigma,
                           self.out_tokens_min, self.out_tokens_max],
            "adapters": [[a, w] for a, w in self.adapters],
            "sampled_fraction": self.sampled_fraction,
            "priorities": [[p, w] for p, w in self.priorities],
            "timeout_s": self.timeout_s,
            "seed": self.seed,
        }


@dataclass
class StreamResult:
    """Everything measured about one scheduled stream. Times are
    seconds on the client loop clock; TTFT/ITL are measured from the
    SCHEDULED arrival, so a stream the saturated server accepted late
    (or never) still counts against the tail."""

    index: int
    scheduled_s: float              # offset from run start
    sent_s: Optional[float] = None  # actual first-byte-out offset
    code: Optional[int] = None      # HTTP status (None: connect failure)
    ttft_s: Optional[float] = None  # first token event - scheduled
    token_gaps_s: list = field(default_factory=list)
    tokens: list = field(default_factory=list)
    done: Optional[dict] = None     # the SSE final summary payload
    retry_after_s: Optional[float] = None
    heartbeats: int = 0
    truncated: bool = False         # SSE body ended without a done event
    aborted: bool = False           # client-side wall-deadline abort
    error: Optional[str] = None
    request: Optional[dict] = None  # the body sent (token accounting)

    @property
    def completed(self) -> bool:
        return (self.code == 200 and self.done is not None
                and self.done.get("status") == "completed")


async def _read_headers(reader) -> dict:
    headers = {}
    while True:
        line = await reader.readline()
        if line in (b"\r\n", b"\n", b""):
            return headers
        name, sep, value = line.decode("latin-1").partition(":")
        if sep:
            headers[name.strip().lower()] = value.strip()


async def _one_stream(host: str, port: int, res: StreamResult,
                      body: dict, t0: float,
                      connect_timeout: float) -> None:
    loop = asyncio.get_running_loop()
    res.request = body
    payload = json.dumps(dict(body, stream=True)).encode()
    try:
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), connect_timeout)
    except Exception as e:
        res.error = f"connect: {type(e).__name__}: {e}"
        return
    try:
        res.sent_s = loop.time() - t0
        writer.write(
            b"POST /v1/completions HTTP/1.1\r\n"
            b"Host: loadgen\r\n"
            b"Content-Type: application/json\r\n"
            b"Connection: close\r\n"
            + f"Content-Length: {len(payload)}\r\n\r\n".encode()
            + payload)
        await writer.drain()
        status_line = await reader.readline()
        parts = status_line.decode("latin-1").split()
        if len(parts) < 2 or not parts[1].isdigit():
            res.error = f"bad status line: {status_line!r}"
            return
        res.code = int(parts[1])
        headers = await _read_headers(reader)
        if "retry-after" in headers:
            try:
                res.retry_after_s = float(headers["retry-after"])
            except ValueError:
                res.retry_after_s = -1.0  # present but unparseable
        ctype = headers.get("content-type", "")
        if "text/event-stream" not in ctype:
            n = int(headers.get("content-length", 0))
            raw = await reader.readexactly(n) if n else b""
            try:
                res.done = json.loads(raw) if raw else None
            except json.JSONDecodeError:
                res.error = "unparseable JSON body"
            return
        # SSE: events separated by blank lines, EOF-terminated.
        last_event_t = None
        data_lines = []
        while True:
            line = await reader.readline()
            if line == b"":
                res.truncated = res.done is None
                return
            line = line.rstrip(b"\r\n")
            if line.startswith(b":"):
                res.heartbeats += 1
                continue
            if line.startswith(b"data:"):
                data_lines.append(line[5:].strip())
                continue
            if line:
                continue  # unknown field; ignore per SSE spec
            if not data_lines:
                continue  # empty event
            event = json.loads(b"\n".join(data_lines))
            data_lines = []
            now = loop.time() - t0
            if event.get("done"):
                res.done = event
            elif "token" in event:
                res.tokens.append(int(event["token"]))
                if res.ttft_s is None:
                    res.ttft_s = now - res.scheduled_s
                else:
                    res.token_gaps_s.append(now - last_event_t)
                last_event_t = now
    except asyncio.IncompleteReadError:
        res.truncated = True
    except asyncio.CancelledError:
        res.aborted = True
        raise
    except Exception as e:  # measurement must survive any one stream
        res.error = f"{type(e).__name__}: {e}"
    finally:
        try:
            writer.close()
        except Exception:
            pass


async def _run_open_loop_async(host: str, port: int,
                               schedule: ArrivalSchedule,
                               profile: TrafficProfile, *,
                               vocab_size: int,
                               connect_timeout: float,
                               wall_deadline_s: Optional[float],
                               on_started=None) -> list:
    loop = asyncio.get_running_loop()
    offsets = schedule.offsets()
    bodies = [profile.sample(vocab_size) for _ in range(schedule.n)]
    t0 = loop.time()
    results = [StreamResult(index=i, scheduled_s=float(offsets[i]))
               for i in range(schedule.n)]

    async def _dispatch(i):
        delay = t0 + offsets[i] - loop.time()
        if delay > 0:
            await asyncio.sleep(delay)
        await _one_stream(host, port, results[i], bodies[i], t0,
                          connect_timeout)

    tasks = [asyncio.ensure_future(_dispatch(i))
             for i in range(schedule.n)]
    if on_started is not None:
        on_started(tasks)
    gather = asyncio.gather(*tasks, return_exceptions=True)
    if wall_deadline_s is not None:
        try:
            await asyncio.wait_for(asyncio.shield(gather), wall_deadline_s)
        except asyncio.TimeoutError:
            for t in tasks:
                t.cancel()
            await asyncio.gather(*tasks, return_exceptions=True)
    else:
        await gather
    return results


def run_open_loop(url: str, schedule: ArrivalSchedule,
                  profile: TrafficProfile, *, vocab_size: int = 256,
                  connect_timeout: float = 10.0,
                  wall_deadline_s: Optional[float] = None) -> dict:
    """Execute the schedule against a gateway at ``url`` from one
    asyncio client loop. Returns ``{"results": [StreamResult...],
    "wall_s": float, "process_cpu_s": float}`` — CPU is
    ``time.process_time`` over the run, i.e. client + (for in-process
    gateways, which is how the tests run) server host cost together.

    ``wall_deadline_s`` bounds the whole run: streams still open at the
    deadline are aborted client-side (their sockets close, exercising
    the gateway's broken-socket cancel) and marked ``aborted`` — they
    count as not-completed in the report, never as errors.
    """
    parsed = urllib.parse.urlsplit(url)
    host, port = parsed.hostname, parsed.port
    if host is None or port is None:
        raise ValueError(f"url must carry an explicit host:port ({url!r})")
    cpu0 = time.process_time()
    wall0 = time.perf_counter()
    results = asyncio.run(_run_open_loop_async(
        host, port, schedule, profile, vocab_size=vocab_size,
        connect_timeout=connect_timeout, wall_deadline_s=wall_deadline_s))
    return {
        "results": results,
        "wall_s": time.perf_counter() - wall0,
        "process_cpu_s": time.process_time() - cpu0,
    }


def fetch_gateway_metrics(url: str, names=("open_sse_streams",
                                           "open_sse_streams_max",
                                           "conn_rejections",
                                           "pressure_sheds")) -> dict:
    """Scrape ``GET /metrics`` and pull out the named
    ``accelerate_tpu_gateway_*`` families (queue-depth / saturation
    observability for reports). Unknown names are simply absent."""
    with urllib.request.urlopen(f"{url}/metrics", timeout=10) as r:
        text = r.read().decode()
    out = {}
    want = {f"accelerate_tpu_gateway_{n}": n for n in names}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and parts[0] in want:
            out[want[parts[0]]] = float(parts[1])
    return out
