"""Open-loop load generation for the serving gateway.

Closed-loop clients (send, wait, send again) cannot see queueing
collapse: when the server slows down, a closed-loop client slows its
own offered load to match, so latency looks flat right through
saturation. Real traffic is open-loop — arrivals keep coming whether
or not the server is keeping up — which is why serving claims here are
gated on this harness rather than on per-request benchmarks.

Three pieces, all stdlib + numpy:

* :class:`~.generator.ArrivalSchedule` — seeded heavy-tailed
  (lognormal / Pareto) or uniform inter-arrival times; offered load is
  a property OF THE SCHEDULE, fixed before the first byte is sent.
* :class:`~.generator.TrafficProfile` — heavy-tailed prompt/output
  lengths and a mixed adapter / sampling-seed / priority request mix.
* :func:`~.generator.run_open_loop` — tens of thousands of scheduled
  SSE streams driven from ONE asyncio client loop, each timestamped
  against its *scheduled* arrival (a stream the server couldn't even
  accept still counts against the tail — that is the open-loop point).

:func:`~.report.build_report` turns the raw per-stream results into
the JSON report consumed by ``bench.py`` (``extra.serving.open_loop``)
and the ``accelerate-tpu loadtest`` CLI: goodput, p50/p99/p99.9 TTFT
and ITL, 429/Retry-After conformance, token-accounting balance, and
host CPU per stream.
"""

from .generator import (ArrivalSchedule, StreamResult, TrafficProfile,
                        fetch_gateway_metrics, run_open_loop)
from .report import build_report, percentile

__all__ = [
    "ArrivalSchedule",
    "TrafficProfile",
    "StreamResult",
    "run_open_loop",
    "fetch_gateway_metrics",
    "build_report",
    "percentile",
]
