"""Request-scoped tracing: trace ids, spans, and Chrome-trace export.

The serving stack's metrics (``serving.metrics``) answer "how is the
fleet doing on average"; this module answers "where did *this* request's
latency go". A ``trace_id`` is minted at the gateway (or accepted from
the client via ``X-Request-Id``), carried on ``Request``/``FleetRequest``
through every lifecycle edge, and each edge drops a span into a
:class:`Tracer`:

* ``queue_wait`` — admission-queue residency (submit → slot assignment)
* ``prefill_chunk`` — each fixed-shape prefill chunk, with offset/backlog
* ``decode_tick`` / ``itl`` — every decode step's wall time, per request
* instant events — prefix-cache hits/aliases, page preemptions,
  speculation accept counts, retirements, failover hops

Spans land in a **lock-light per-thread ring buffer**: the hot path is a
single list-index store by the owning thread (no locks, no allocation
beyond one tuple), bounded with drop-oldest semantics so a tracer can
stay enabled in production indefinitely. Export is Chrome-trace /
Perfetto JSON (``chrome://tracing``, https://ui.perfetto.dev) via
:meth:`Tracer.chrome_trace` / :meth:`Tracer.dump`, surfaced as
``engine.dump_trace(path)``, gateway ``GET /debug/trace?id=`` and
``accelerate-tpu serve --trace-dir``.

Timestamps are ``time.monotonic()`` microseconds: within one process all
tracers share the clock, so per-replica traces merge into one aligned
fleet timeline (:func:`merge_chrome_traces`).
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "Tracer",
    "TraceSpan",
    "new_trace_id",
    "clean_trace_id",
    "merge_chrome_traces",
    "validate_chrome_trace",
]

#: Cap on client-supplied X-Request-Id values.
TRACE_ID_MAX_LEN = 128

_TRACE_ID_CHARS = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_.:")


def new_trace_id() -> str:
    """Mint a fresh 16-hex-char trace id."""
    return uuid.uuid4().hex[:16]


def clean_trace_id(raw: Any) -> Optional[str]:
    """Sanitize a client-supplied trace id (``X-Request-Id`` header).

    Returns the id if it is a non-empty string of reasonable length over
    ``[A-Za-z0-9._:-]``, else ``None`` (caller mints a fresh one).
    """
    if not isinstance(raw, str):
        return None
    raw = raw.strip()
    if not raw or len(raw) > TRACE_ID_MAX_LEN:
        return None
    if not all(c in _TRACE_ID_CHARS for c in raw):
        return None
    return raw


class _Ring:
    """Single-writer bounded ring with drop-oldest semantics.

    The owning thread appends lock-free (one index store + increment);
    readers on other threads take a best-effort snapshot — records are
    immutable tuples, so a concurrent reader can miss or double-see the
    entry being overwritten but never observes a torn record. ``start``
    is a logical watermark so :meth:`Tracer.clear` can discard history
    without touching the writer's buffer.
    """

    __slots__ = ("buf", "cap", "n", "start")

    def __init__(self, cap: int):
        self.cap = cap
        self.buf: List[Optional[tuple]] = [None] * cap
        self.n = 0
        self.start = 0

    def append(self, rec: tuple) -> None:
        n = self.n
        self.buf[n % self.cap] = rec
        self.n = n + 1

    def snapshot(self) -> List[tuple]:
        n = self.n
        lo = max(self.start, n - self.cap)
        buf, cap = self.buf, self.cap
        out = []
        for i in range(lo, n):
            rec = buf[i % cap]
            if rec is not None:
                out.append(rec)
        return out


class TraceSpan:
    """Context manager emitting one complete span on exit.

    Returned by :meth:`Tracer.span`; ``args`` may be extended inside the
    ``with`` block via :meth:`note` (e.g. recording a hit count that is
    only known at the end of the timed region).
    """

    __slots__ = ("_tracer", "name", "cat", "trace_id", "args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, cat: str,
                 trace_id: Optional[str], args: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.cat = cat
        self.trace_id = trace_id
        self.args = args
        self._t0 = 0.0

    def note(self, **fields: Any) -> None:
        if self.args is None:
            self.args = {}
        self.args.update(fields)

    def __enter__(self) -> "TraceSpan":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> None:
        self._tracer.emit(self.name, self._t0,
                          time.monotonic() - self._t0,
                          trace_id=self.trace_id, cat=self.cat,
                          args=self.args)


_PID_LOCK = threading.Lock()
_NEXT_PID = [1]


def _next_pid() -> int:
    with _PID_LOCK:
        pid = _NEXT_PID[0]
        _NEXT_PID[0] += 1
    return pid


class Tracer:
    """Bounded, lock-light span sink with Chrome-trace export.

    One tracer per replica (engine) or per training session. Each
    emitting thread gets its own :class:`_Ring` of ``capacity`` records;
    the registry lock is taken only on a thread's *first* emit. With
    ``enabled=False`` every emit is a cheap early return, so call sites
    never need their own guards.

    Record layout (immutable tuple):
    ``(t0_monotonic_s, dur_s_or_None, name, cat, trace_id, args)`` —
    ``dur_s=None`` marks an instant event.
    """

    def __init__(self, capacity: int = 4096, enabled: bool = True,
                 name: str = "trace"):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self.name = name
        self.pid = _next_pid()
        self._rings: Dict[int, _Ring] = {}
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- hot path ------------------------------------------------------
    def emit(self, name: str, t0: float, dur_s: Optional[float] = None, *,
             trace_id: Optional[str] = None, cat: str = "serving",
             args: Optional[Dict[str, Any]] = None) -> None:
        """Record one span (``dur_s`` seconds) or instant (``dur_s=None``)."""
        if not self.enabled:
            return
        ring = getattr(self._local, "ring", None)
        if ring is None:
            ring = self._register_ring()
        ring.append((t0, dur_s, name, cat, trace_id, args))

    def instant(self, name: str, *, trace_id: Optional[str] = None,
                cat: str = "serving",
                args: Optional[Dict[str, Any]] = None) -> None:
        self.emit(name, time.monotonic(), None, trace_id=trace_id,
                  cat=cat, args=args)

    def span(self, name: str, *, trace_id: Optional[str] = None,
             cat: str = "serving",
             args: Optional[Dict[str, Any]] = None) -> TraceSpan:
        return TraceSpan(self, name, cat, trace_id, args)

    def _register_ring(self) -> _Ring:
        ring = _Ring(self.capacity)
        self._local.ring = ring
        with self._lock:
            if len(self._rings) >= 32:
                # Short-lived emitters (e.g. per-connection HTTP handler
                # threads calling submit) would otherwise leak one ring
                # per dead thread; prune rings whose thread is gone.
                live = {t.ident for t in threading.enumerate()}
                for tid in [t for t in self._rings if t not in live]:
                    del self._rings[tid]
            self._rings[threading.get_ident()] = ring
        return ring

    # -- export --------------------------------------------------------
    def events(self, trace_id: Optional[str] = None) -> List[tuple]:
        """Snapshot of buffered records (optionally filtered), as
        ``(tid, t0, dur_s, name, cat, trace_id, args)`` sorted by t0."""
        with self._lock:
            rings = list(self._rings.items())
        out = []
        for tid, ring in rings:
            for rec in ring.snapshot():
                if trace_id is None or rec[4] == trace_id:
                    out.append((tid,) + rec)
        out.sort(key=lambda r: r[1])
        return out

    def chrome_trace(self, trace_id: Optional[str] = None) -> Dict[str, Any]:
        """Chrome-trace/Perfetto JSON dict for the buffered spans."""
        evs: List[Dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": self.pid, "tid": 0,
            "args": {"name": self.name},
        }]
        for tid, t0, dur, name, cat, tr, args in self.events(trace_id):
            ev: Dict[str, Any] = {
                "name": name, "cat": cat, "pid": self.pid, "tid": tid,
                "ts": round(t0 * 1e6, 3),
            }
            a = dict(args) if args else {}
            if tr is not None:
                a["trace_id"] = tr
            if a:
                ev["args"] = a
            if dur is None:
                ev["ph"] = "i"
                ev["s"] = "t"
            else:
                ev["ph"] = "X"
                ev["dur"] = round(dur * 1e6, 3)
            evs.append(ev)
        return {"traceEvents": evs, "displayTimeUnit": "ms"}

    def dump(self, path: str, trace_id: Optional[str] = None) -> str:
        """Write the Chrome-trace JSON to ``path``; returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.chrome_trace(trace_id), f)
        return path

    def clear(self) -> None:
        """Discard buffered spans (e.g. after warmup traffic)."""
        with self._lock:
            rings = list(self._rings.values())
        for ring in rings:
            ring.start = ring.n

    def __len__(self) -> int:
        with self._lock:
            rings = list(self._rings.values())
        return sum(max(0, min(r.n - r.start, r.cap)) for r in rings)


def merge_chrome_traces(traces: Iterable[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-replica Chrome-trace dicts into one fleet timeline.

    Tracers in one process share the monotonic clock and carry distinct
    ``pid`` lanes, so concatenating event lists yields an aligned
    multi-process view (replica A's prefill next to replica B's resumed
    continuation after a failover).
    """
    evs: List[Dict[str, Any]] = []
    for t in traces:
        evs.extend(t.get("traceEvents", ()))
    return {"traceEvents": evs, "displayTimeUnit": "ms"}


def validate_chrome_trace(trace: Dict[str, Any]) -> List[str]:
    """Structural lint of a Chrome-trace dict; returns problems (empty
    list = valid). Used by tests and by ``/debug/trace`` consumers that
    want a cheap sanity check without loading the Perfetto UI."""
    problems: List[str] = []
    evs = trace.get("traceEvents")
    if not isinstance(evs, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            problems.append(f"event {i}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M", "B", "E", "C"):
            problems.append(f"event {i}: unknown ph {ph!r}")
            continue
        if ph == "M":
            continue
        for key in ("name", "pid", "tid", "ts"):
            if key not in ev:
                problems.append(f"event {i} ({ev.get('name')}): missing {key}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"event {i} ({ev.get('name')}): bad dur {dur!r}")
    return problems
