"""Per-replica flight recorder: the serving engine's black box.

Keeps the last N *structured* lifecycle events — admissions,
preemptions, pool exhaustion, adapter loads, XLA compile events, kill
injections, fatal errors — in a bounded deque, and renders them as a
postmortem dict on demand. The engine auto-captures a dump when its run
loop dies (``kill()`` or an engine fatal), so the router's failover
report carries *what the replica was doing when it died* instead of just
a stack trace.

Events are mirrored as instant events into the replica's
:class:`~accelerate_tpu.observability.tracing.Tracer` (when one is
attached), so a Chrome-trace export shows the black-box events on the
same timeline as the request spans.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .tracing import Tracer

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """Bounded (drop-oldest) recorder of structured replica events.

    Recording takes one short lock (events are orders of magnitude rarer
    than decode ticks — admissions, preemptions, compiles — so a deque
    under a lock is plenty); reading snapshots under the same lock.
    """

    def __init__(self, capacity: int = 256, name: str = "replica",
                 tracer: Optional[Tracer] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.name = name
        self._events: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._tracer = tracer
        self._dropped = 0

    def record(self, kind: str, **fields: Any) -> None:
        """Append one event; drops the oldest when full."""
        ev = {"ts": time.monotonic(), "kind": kind}
        if fields:
            ev.update(fields)
        with self._lock:
            if len(self._events) == self.capacity:
                self._dropped += 1
            self._events.append(ev)
        if self._tracer is not None:
            self._tracer.instant(kind, trace_id=fields.get("trace_id"),
                                 cat="flight", args=fields or None)

    def snapshot(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            events = list(self._events)
        if last is not None:
            events = events[-last:]
        return events

    def dump(self) -> Dict[str, Any]:
        """Postmortem dict: recorder identity + the buffered events."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        return {
            "name": self.name,
            "captured_at": time.time(),
            "captured_monotonic": time.monotonic(),
            "capacity": self.capacity,
            "dropped": dropped,
            "events": events,
        }

    def dump_json(self, path: str) -> str:
        """Write :meth:`dump` to ``path`` (values coerced via ``repr`` if
        not JSON-serializable); returns ``path``."""
        with open(path, "w") as f:
            json.dump(self.dump(), f, default=repr)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)
