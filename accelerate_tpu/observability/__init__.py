"""Low-overhead observability for the serving and training stack.

Three pieces, one timeline format:

* :mod:`~accelerate_tpu.observability.tracing` — request-scoped spans in
  lock-light per-thread ring buffers, exported as Chrome-trace/Perfetto
  JSON. A ``trace_id`` minted at the gateway (or taken from
  ``X-Request-Id``) follows a request through queue wait, prefill
  chunks, decode ticks, preemptions and failover hops across replicas.
* :mod:`~accelerate_tpu.observability.flight_recorder` — the last N
  structured events per replica (admissions, preemptions, pool
  exhaustion, adapter loads, compile events, fatals), auto-dumped on
  engine death so failover reports carry a postmortem.
* :mod:`~accelerate_tpu.observability.promlint` — a small Prometheus
  text-exposition validator used to keep ``/metrics`` scrape-clean.

The compile-event counterpart, ``CompileWatcher``, lives in
:mod:`accelerate_tpu.utils.profiling` next to ``ProfileSession`` (which
emits the same span format for training steps).
"""

from .flight_recorder import FlightRecorder
from .promlint import lint_prometheus_text, parse_sample_line
from .tracing import (
    Tracer,
    TraceSpan,
    clean_trace_id,
    merge_chrome_traces,
    new_trace_id,
    validate_chrome_trace,
)

__all__ = [
    "FlightRecorder",
    "Tracer",
    "TraceSpan",
    "clean_trace_id",
    "merge_chrome_traces",
    "new_trace_id",
    "validate_chrome_trace",
    "lint_prometheus_text",
    "parse_sample_line",
]
