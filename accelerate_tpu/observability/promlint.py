"""Minimal Prometheus text-exposition (0.0.4) linter.

Validates the gateway's ``/metrics`` body without external
dependencies: every sample series must be preceded by ``# HELP`` and
``# TYPE`` lines for its family, histogram families must expose
cumulative ``_bucket{le=...}`` series ending in ``le="+Inf"`` with a
matching ``_count``, and no family may be declared twice. Used by the
exposition-format lint test and available to deployments that want to
gate a scrape config on a known-good body.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

__all__ = ["lint_prometheus_text", "parse_sample_line"]

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>\S+)(?:\s+\d+)?$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

#: suffixes a histogram (or summary) family fans out into
_FAMILY_SUFFIXES = ("_bucket", "_sum", "_count")


def parse_sample_line(line: str) -> Optional[Tuple[str, Dict[str, str], str]]:
    """``(name, labels, value)`` for a sample line, or None if malformed."""
    m = _SAMPLE_RE.match(line)
    if m is None:
        return None
    labels: Dict[str, str] = {}
    raw = m.group("labels")
    if raw:
        consumed = 0
        for lm in _LABEL_RE.finditer(raw):
            labels[lm.group(1)] = lm.group(2)
            consumed = lm.end()
        # tolerate the trailing comma prometheus allows; reject garbage
        if raw[consumed:].strip(", ") != "":
            return None
    return m.group("name"), labels, m.group("value")


def _family_of(name: str, types: Dict[str, str]) -> str:
    """Map a sample name to its declared family (histogram samples like
    ``x_bucket`` belong to family ``x``)."""
    if name in types:
        return name
    for suffix in _FAMILY_SUFFIXES:
        if name.endswith(suffix):
            base = name[: -len(suffix)]
            if types.get(base) in ("histogram", "summary"):
                return base
    return name


def lint_prometheus_text(text: str) -> List[str]:
    """Lint an exposition body; returns problems (empty list = valid)."""
    problems: List[str] = []
    helps: Dict[str, str] = {}
    types: Dict[str, str] = {}
    samples: List[Tuple[int, str, Dict[str, str], float]] = []

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            parts = line.split(None, 3)
            if len(parts) < 3:
                problems.append(f"line {lineno}: malformed HELP")
                continue
            name = parts[2]
            if name in helps:
                problems.append(f"line {lineno}: duplicate HELP for {name}")
            helps[name] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) != 4:
                problems.append(f"line {lineno}: malformed TYPE")
                continue
            name, mtype = parts[2], parts[3]
            if name in types:
                problems.append(f"line {lineno}: duplicate TYPE for {name}")
            if mtype not in ("counter", "gauge", "histogram", "summary",
                             "untyped"):
                problems.append(
                    f"line {lineno}: unknown type {mtype!r} for {name}")
            types[name] = mtype
            continue
        if line.startswith("#"):
            continue  # plain comment
        parsed = parse_sample_line(line)
        if parsed is None:
            problems.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name, labels, raw_value = parsed
        if not _NAME_RE.match(name):
            problems.append(f"line {lineno}: invalid metric name {name!r}")
            continue
        try:
            value = float(raw_value)
        except ValueError:
            problems.append(
                f"line {lineno}: non-numeric value {raw_value!r} for {name}")
            continue
        samples.append((lineno, name, labels, value))

    seen_series = set()
    hist_buckets: Dict[str, List[Tuple[str, float]]] = {}
    hist_counts: Dict[str, float] = {}
    for lineno, name, labels, value in samples:
        family = _family_of(name, types)
        if family not in helps:
            problems.append(f"line {lineno}: {name} has no # HELP ({family})")
        if family not in types:
            problems.append(f"line {lineno}: {name} has no # TYPE ({family})")
        key = (name, tuple(sorted(labels.items())))
        if key in seen_series:
            problems.append(f"line {lineno}: duplicate series {name}{labels}")
        seen_series.add(key)
        if types.get(family) == "histogram":
            if name == family + "_bucket":
                le = labels.get("le")
                if le is None:
                    problems.append(
                        f"line {lineno}: histogram bucket without le label")
                else:
                    hist_buckets.setdefault(family, []).append((le, value))
            elif name == family + "_count":
                hist_counts[family] = value
            elif name not in (family + "_sum",):
                problems.append(
                    f"line {lineno}: unexpected histogram sample {name}")

    for family, mtype in types.items():
        if mtype != "histogram":
            continue
        buckets = hist_buckets.get(family)
        if not buckets:
            problems.append(f"histogram {family}: no _bucket samples")
            continue
        if buckets[-1][0] != "+Inf":
            problems.append(
                f"histogram {family}: buckets do not end in le=\"+Inf\" "
                f"(last le={buckets[-1][0]!r})")
        prev_le, prev_count = None, None
        for le, count in buckets:
            le_f = float("inf") if le == "+Inf" else float(le)
            if prev_le is not None:
                if le_f <= prev_le:
                    problems.append(
                        f"histogram {family}: le={le} out of order")
                if count < prev_count:
                    problems.append(
                        f"histogram {family}: bucket counts not cumulative "
                        f"(le={le} count {count} < {prev_count})")
            prev_le, prev_count = le_f, count
        if family in hist_counts and buckets[-1][1] != hist_counts[family]:
            problems.append(
                f"histogram {family}: _count {hist_counts[family]} != "
                f"+Inf bucket {buckets[-1][1]}")

    return problems
