"""The Accelerator: top-level orchestration API.

Capability parity with the reference's ``accelerator.py`` (reference:
src/accelerate/accelerator.py — Accelerator :160, prepare :1211, backward
:2164, accumulate :1046, clip_grad_norm_ :2292, gather_for_metrics :2408,
save_state :2915, load_state :3081, autocast :3383, profile :3423,
set_trigger/check_trigger :2198-2255, join_uneven_inputs :1091,
free_memory :3219).

TPU-native redesign (SURVEY.md §7 design stance): instead of mutating torch
modules and hooking autograd, ``prepare`` *captures* a pure apply-fn +
parameter pytree into compiled steps with explicit GSPMD sharding:

* ``model(params-free call)`` → jitted forward with the precision policy.
* ``accelerator.backward(loss_fn, batch)`` → jitted value_and_grad; the
  global-batch mean makes XLA emit the data-parallel gradient reduction, so
  there is no DDP/no_sync machinery — "not syncing" is simply not applying
  the optimizer (gradients accumulate in a device-side buffer).
* The fused fast path ``compile_train_step`` folds forward+backward+
  accumulate(scan)+clip+update into ONE executable with donated buffers —
  this is the path benchmarks use.
"""

from __future__ import annotations

import contextlib
import functools
import inspect
import math
import os
import warnings
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .data_loader import DataLoaderShard, batch_sharding, prepare_data_loader, skip_first_batches
from .optimizer import AcceleratedOptimizer
from .parallel.mesh import MeshConfig
from .parallel.sharding import infer_param_shardings, replicated_sharding, shard_params, sharding_summary
from .precision import Policy, policy_for, scale_loss
from .scheduler import AcceleratedScheduler, LRScheduler
from .state import AcceleratorState, GradientState, PartialState
from .utils.dataclasses import (
    AutocastKwargs,
    DataLoaderConfiguration,
    DistributedInitKwargs,
    DistributedType,
    FullyShardedDataParallelPlugin,
    GradientAccumulationPlugin,
    GradScalerKwargs,
    JitConfig,
    PrecisionType,
    ProfileKwargs,
    ProjectConfiguration,
)
from .utils.operations import (
    broadcast,
    concatenate,
    convert_to_fp32,
    gather,
    gather_object,
    pad_across_processes,
    recursively_apply,
    reduce,
    send_to_device,
)


def _is_optax_tx(obj) -> bool:
    return hasattr(obj, "init") and hasattr(obj, "update") and not hasattr(obj, "apply")


def _is_flax_module(obj) -> bool:
    try:
        import flax.linen as nn

        return isinstance(obj, nn.Module)
    except ImportError:
        return False


def _is_dataloader_like(obj) -> bool:
    from collections.abc import Mapping

    return (
        hasattr(obj, "__iter__")
        and not isinstance(obj, (Mapping, list, tuple, str))
        and not _is_flax_module(obj)
    )


def _is_scheduler_like(obj) -> bool:
    return hasattr(obj, "step") and hasattr(obj, "get_last_lr")


class Model:
    """A model = pure apply_fn + parameter pytree.

    Construct from a flax module (``Model(module, params)``) or any pure
    function (``Model(apply_fn, params)`` with signature
    ``apply_fn(params, *inputs, rngs=None)``).
    """

    def __init__(self, module_or_fn, params, apply_kwargs: Optional[dict] = None):
        if _is_flax_module(module_or_fn):
            self.module = module_or_fn
            _apply = module_or_fn.apply

            def apply_fn(p, *args, **kwargs):
                variables = p if isinstance(p, dict) and "params" in p else {"params": p}
                return _apply(variables, *args, **kwargs)

            self.apply_fn = apply_fn
        else:
            self.module = None
            self.apply_fn = module_or_fn
        self.params = params
        self.apply_kwargs = apply_kwargs or {}


class AcceleratedModel:
    """A prepared model: sharded params + policy-compiled forward
    (the counterpart of the reference's wrapped torch module)."""

    def __init__(self, model: Model, policy: Policy, mesh, param_shardings, autocast_enabled: bool = True):
        self.module = model.module
        self.apply_fn = model.apply_fn
        self.params = model.params
        self.policy = policy if autocast_enabled else Policy()
        self.mesh = mesh
        self.param_shardings = param_shardings
        self._fwd_jit = None
        self.training = True

    def eval(self):
        """Switch to inference mode (dropout off via deterministic apply)."""
        self.training = False
        return self

    def train(self, mode: bool = True):
        """Switch training mode (reference nn.Module.train parity)."""
        self.training = mode
        return self

    def __call__(self, *args, **kwargs):
        """Jitted inference forward: params cast to compute dtype, outputs to
        fp32 (reference: autocast-wrap forward + fp32 outputs,
        accelerator.py:1389-1398).

        Non-array kwargs (flags like ``deterministic=True``) are treated as
        STATIC — each combination gets its own compiled executable — so
        Python control flow on them inside the module works.
        """
        import numpy as _np

        traced_kwargs = {k: v for k, v in kwargs.items() if isinstance(v, (jax.Array, _np.ndarray))}
        static_kwargs = {k: v for k, v in kwargs.items() if k not in traced_kwargs}
        try:
            static_key = tuple(sorted(static_kwargs.items()))
        except TypeError:  # unhashable static value: fall back to eager apply
            out = self.apply_fn(self.policy.cast_to_compute(self.params), *args, **kwargs)
            return self.policy.cast_to_output(out)

        if self._fwd_jit is None:
            self._fwd_jit = {}
        if static_key not in self._fwd_jit:
            apply_fn, policy = self.apply_fn, self.policy
            frozen_static = dict(static_kwargs)

            @jax.jit
            def fwd(params, args, traced):
                out = apply_fn(policy.cast_to_compute(params), *args, **traced, **frozen_static)
                return policy.cast_to_output(out)

            self._fwd_jit[static_key] = fwd
        return self._fwd_jit[static_key](self.params, args, traced_kwargs)

    def state_dict(self):
        """The current parameter pytree (reference state_dict parity)."""
        return self.params

    def load_state_dict(self, params):
        """Replace params, re-placing them into this model's shardings."""
        self.params = shard_params(params, self.param_shardings) if self.param_shardings is not None else params


class Accelerator:
    """Creates the distributed/mesh environment and prepares objects for it
    (reference: accelerator.py:160)."""

    def __init__(
        self,
        device_placement: bool = True,
        split_batches: bool = False,
        mixed_precision: PrecisionType | str | None = None,
        gradient_accumulation_steps: int = 1,
        cpu: bool = False,
        dataloader_config: Optional[DataLoaderConfiguration] = None,
        deepspeed_plugin=None,
        fsdp_plugin: Optional[FullyShardedDataParallelPlugin] = None,
        megatron_lm_plugin=None,
        tp_plugin=None,
        cp_plugin=None,
        pp_plugin=None,
        ep_plugin=None,
        mesh_config: Optional[MeshConfig] = None,
        rng_types: Optional[list] = None,
        log_with=None,
        project_dir: Optional[str] = None,
        project_config: Optional[ProjectConfiguration] = None,
        gradient_accumulation_plugin: Optional[GradientAccumulationPlugin] = None,
        step_scheduler_with_optimizer: bool = True,
        kwargs_handlers: Optional[list] = None,
        dynamo_backend=None,
        jit_config: Optional[JitConfig] = None,
        seed: int = 0,
    ):
        self.project_configuration = project_config or ProjectConfiguration(project_dir=project_dir)
        if project_dir is not None and self.project_configuration.project_dir is None:
            self.project_configuration.set_directories(project_dir)

        # Opt-in persistent compile cache: a relaunched trainer (preemption,
        # --max_restarts) skips recompilation entirely. Env-gated so library
        # import never mutates global jax config uninvited.
        if os.environ.get("ACCELERATE_TPU_COMPILATION_CACHE"):
            from .utils.platforms import enable_compilation_cache

            enable_compilation_cache()

        # kwargs handlers (reference: accelerator.py:347-381)
        self.autocast_handler: Optional[AutocastKwargs] = None
        self.scaler_handler: Optional[GradScalerKwargs] = None
        self.init_handler: Optional[DistributedInitKwargs] = None
        self.profile_handler: Optional[ProfileKwargs] = None
        for handler in kwargs_handlers or []:
            if isinstance(handler, AutocastKwargs):
                self.autocast_handler = handler
            elif isinstance(handler, GradScalerKwargs):
                self.scaler_handler = handler
            elif isinstance(handler, DistributedInitKwargs):
                self.init_handler = handler
            elif isinstance(handler, ProfileKwargs):
                self.profile_handler = handler

        self.state = AcceleratorState(
            mixed_precision=str(mixed_precision) if mixed_precision is not None else None,
            cpu=cpu,
            mesh_config=mesh_config,
            fsdp_plugin=fsdp_plugin,
            tp_plugin=tp_plugin,
            cp_plugin=cp_plugin,
            pp_plugin=pp_plugin,
            ep_plugin=ep_plugin,
            deepspeed_plugin=deepspeed_plugin,
            megatron_lm_plugin=megatron_lm_plugin,
            _from_accelerator=True,
            init_kwargs=self.init_handler,
        )

        if gradient_accumulation_plugin is None:
            gradient_accumulation_plugin = GradientAccumulationPlugin(num_steps=gradient_accumulation_steps)
        self.gradient_state = GradientState(gradient_accumulation_plugin)

        self.device_placement = device_placement
        self.split_batches = split_batches
        self.dataloader_config = dataloader_config or DataLoaderConfiguration(split_batches=split_batches)
        self.step_scheduler_with_optimizer = step_scheduler_with_optimizer
        self.rng_types = rng_types or ["numpy", "python"]
        self.jit_config = jit_config or JitConfig()
        self.jit_config.apply()

        self.policy = policy_for(self.state.mixed_precision)
        self._use_loss_scaling = self.state.mixed_precision == "fp16"

        self._models: list[AcceleratedModel] = []
        self._optimizers: list[AcceleratedOptimizer] = []
        self._schedulers: list[AcceleratedScheduler] = []
        self._dataloaders: list[DataLoaderShard] = []
        self._custom_objects: list = []
        self.step = 0  # accumulation step counter (reference: accelerator.py:1020)
        self._rng_key = jax.random.PRNGKey(seed)
        from collections import OrderedDict

        from .serving.metrics import GatewayStats, ServingStats
        from .utils.profiling import PipelineStats

        # Shared across every prepared loader so step-time breakdowns
        # (data_wait_ms/stage_ms/queue depth) aggregate in one place.
        self.pipeline_stats = PipelineStats()
        # Shared by ServingEngine(accelerator=...) instances so serving
        # counters (TTFT, queue wait, tokens/sec, occupancy) surface through
        # log(include_serving=True) / serving_metrics() / profile().
        self.serving_stats = ServingStats()
        # Same sharing for ServingGateway(accelerator=...): HTTP counters
        # (requests by status class, streams, in-flight) surface through
        # log(include_gateway=True) / gateway_metrics() / profile().
        self.gateway_stats = GatewayStats()
        self._backward_cache: OrderedDict = OrderedDict()
        self._backward_cache_size = 16
        self._fused_cache: dict = {}
        self.flag_tensor = None
        self._log_with = log_with
        self.trackers: list = []
        from .logging import get_logger

        self.logger = get_logger(__name__)

    # ------------------------------------------------------------------
    # State passthrough (reference: accelerator.py properties)
    # ------------------------------------------------------------------

    @property
    def mesh(self):
        """The live jax.sharding.Mesh every prepared object is laid out over."""
        return self.state.mesh

    @property
    def distributed_type(self):
        """The governing strategy (reference DistributedType parity)."""
        return self.state.distributed_type

    @property
    def num_processes(self):
        """Process (host) count in the world."""
        return self.state.num_processes

    @property
    def process_index(self):
        """This process's global rank."""
        return self.state.process_index

    @property
    def local_process_index(self):
        """This process's rank on its machine."""
        return self.state.local_process_index

    @property
    def device(self):
        """This process's first addressable device."""
        return self.state.device

    @property
    def is_main_process(self):
        """True on global rank 0."""
        return self.state.is_main_process

    @property
    def is_local_main_process(self):
        """True on each machine's rank-0 process."""
        return self.state.is_local_main_process

    @property
    def is_last_process(self):
        """True on the highest-ranked process."""
        return self.state.is_last_process

    @property
    def mixed_precision(self):
        """The active precision policy name ("no"/"bf16"/"fp16"/"fp8")."""
        return self.state.mixed_precision

    @property
    def use_distributed(self):
        """True in any multi-process world."""
        return self.state.use_distributed

    @property
    def sync_gradients(self):
        """True when the current accumulation window ends at this step."""
        return self.gradient_state.sync_gradients

    @property
    def gradient_accumulation_steps(self):
        """Microbatches per optimizer update."""
        return self.gradient_state.num_steps

    @gradient_accumulation_steps.setter
    def gradient_accumulation_steps(self, num_steps: int):
        self.gradient_state.plugin_kwargs.update({"num_steps": num_steps})

    @property
    def even_batches(self):
        """Default tail-padding behavior for prepared loaders (reference: :571)."""
        return self.dataloader_config.even_batches

    @even_batches.setter
    def even_batches(self, value: bool):
        self.dataloader_config.even_batches = value

    @property
    def project_dir(self):
        """Root directory for checkpoints/logs (ProjectConfiguration)."""
        return self.project_configuration.project_dir

    def on_main_process(self, function):
        """Decorator: run ``function`` on global rank 0 only (reference: :2665)."""
        return PartialState().on_main_process(function)

    def on_local_main_process(self, function):
        """Decorator: run ``function`` on each machine's rank 0 only."""
        return PartialState().on_local_main_process(function)

    def on_process(self, function=None, process_index=None):
        """Decorator: run ``function`` on one specific rank only."""
        return PartialState().on_process(function, process_index=process_index)

    def wait_for_everyone(self):
        """Cross-process barrier (reference: :2810)."""
        PartialState().wait_for_everyone()

    def print(self, *args, **kwargs):
        """print() on the main process only."""
        PartialState().print(*args, **kwargs)

    def split_between_processes(self, inputs, apply_padding: bool = False):
        """Context yielding this process's slice of ``inputs`` (reference: :740)."""
        return PartialState().split_between_processes(inputs, apply_padding=apply_padding)

    # ------------------------------------------------------------------
    # prepare (reference: accelerator.py:1211)
    # ------------------------------------------------------------------

    def prepare(self, *args, device_placement=None):
        """Prepare models/optimizers/dataloaders/schedulers in one call,
        returning them in the same order (reference: accelerator.py:1211).

        Models may be passed as a :class:`Model`, or as a flax module
        followed immediately by its params pytree (the pair is consumed as
        one model).
        """
        # Fuse (module, params) adjacent pairs into Model objects.
        from collections.abc import Mapping

        fused_args: list = []
        skip_next = False
        for i, obj in enumerate(args):
            if skip_next:
                skip_next = False
                continue
            # Params may be dicts or flax FrozenDicts (any Mapping).
            if _is_flax_module(obj) and i + 1 < len(args) and isinstance(args[i + 1], Mapping):
                fused_args.append(Model(obj, args[i + 1]))
                skip_next = True
            else:
                fused_args.append(obj)

        prepared = [self._prepare_one(obj) for obj in fused_args]

        # Bind optimizers to models in order of appearance: the k-th optimizer
        # pairs with the k-th model (reference pairs them implicitly via the
        # params the user constructed the optimizer with).
        models = [p for p in prepared if isinstance(p, AcceleratedModel)]
        opts_in_order = [p for p in prepared if isinstance(p, AcceleratedOptimizer)]
        for k, opt in enumerate(opts_in_order):
            if opt._model is None and models:
                bound = models[k] if k < len(models) else models[0]
                opt._model = bound
                if opt.opt_state is None:
                    opt.init_state(bound.params)

        # Bind schedulers to optimizers (reference: prepare_scheduler :2123).
        opts = [p for p in prepared if isinstance(p, AcceleratedOptimizer)]
        for sched in (p for p in prepared if isinstance(p, AcceleratedScheduler)):
            if not sched.optimizers and opts:
                sched.optimizers = opts

        return prepared[0] if len(prepared) == 1 else tuple(prepared)

    def _prepare_one(self, obj):
        if isinstance(obj, (AcceleratedModel, AcceleratedOptimizer, AcceleratedScheduler, DataLoaderShard)):
            return obj
        if isinstance(obj, Model):
            return self.prepare_model(obj)
        if _is_optax_tx(obj):
            return self.prepare_optimizer(obj)
        if _is_scheduler_like(obj):
            return self.prepare_scheduler(obj)
        if _is_dataloader_like(obj):
            return self.prepare_data_loader(obj)
        return obj

    def prepare_model(self, model: Model, device_placement: Optional[bool] = None, evaluation_mode: bool = False):
        """Shard + place model params per the active parallelism policy
        (reference: accelerator.py:1349)."""
        if not isinstance(model, Model):
            raise TypeError(
                "prepare_model expects an accelerate_tpu.Model (apply_fn/module + params); "
                f"got {type(model)}. Pass Model(module, params)."
            )
        shardings = infer_param_shardings(
            model.params,
            self.mesh,
            fsdp_plugin=self.state.fsdp_plugin,
            tp_plugin=self.state.tp_plugin,
            pp_plugin=self.state.pp_plugin,
            ep_plugin=self.state.ep_plugin,
        )
        if device_placement if device_placement is not None else self.device_placement:
            model.params = shard_params(model.params, shardings)
        autocast_enabled = self.autocast_handler.enabled if self.autocast_handler is not None else True
        wrapped = AcceleratedModel(model, self.policy, self.mesh, shardings, autocast_enabled=autocast_enabled)
        if evaluation_mode:
            wrapped.eval()
        self._models.append(wrapped)
        self.logger.debug("Param sharding summary: %s", sharding_summary(shardings))
        return wrapped

    def prepare_optimizer(self, tx, device_placement: Optional[bool] = None):
        """Wrap an optax transformation (reference: prepare_optimizer :2082).

        With ``fsdp_plugin.cpu_offload=True`` (or a DeepSpeed config naming a
        cpu offload device — reference: accelerator.py:1806-1809) the
        optimizer state lives in pinned host memory between steps
        (parallel/host_offload.py).
        """
        fsdp = self.state.fsdp_plugin
        offload = bool(fsdp is not None and fsdp.cpu_offload)
        if offload:
            from .parallel.host_offload import supports_host_memory

            if not supports_host_memory():
                warnings.warn(
                    "fsdp_plugin.cpu_offload=True but this backend exposes no "
                    "pinned_host memory space; optimizer state stays in device memory.",
                    stacklevel=2,
                )
                offload = False
        opt = AcceleratedOptimizer(
            tx,
            scaler_kwargs=self.scaler_handler,
            use_loss_scaling=self._use_loss_scaling,
            mesh=self.mesh,
            offload_to_host=offload,
            zero_sharding=self.zero_sharding,
        )
        self._optimizers.append(opt)
        return opt

    @property
    def zero_sharding(self) -> bool:
        """Whether optimizer state is ZeRO-sharded over the dp/fsdp axis —
        set on :class:`MeshConfig`, the FSDP plugin, or via DeepSpeed
        ``zero_stage >= 1`` (utils/dataclasses.py)."""
        mesh_cfg = getattr(self.state, "mesh_config", None)
        fsdp = self.state.fsdp_plugin
        return bool(
            getattr(mesh_cfg, "zero_sharding", False)
            or (fsdp is not None and getattr(fsdp, "zero_sharding", False))
        )

    def prepare_scheduler(self, scheduler):
        wrapped = AcceleratedScheduler(
            scheduler,
            optimizers=[],
            step_with_optimizer=self.step_scheduler_with_optimizer,
            split_batches=self.dataloader_config.split_batches,
        )
        self._schedulers.append(wrapped)
        return wrapped

    def prepare_data_loader(self, data_loader, device_placement=None, slice_fn_for_dispatch=None):
        cfg = self.dataloader_config
        dl = prepare_data_loader(
            data_loader,
            mesh=self.mesh,
            split_batches=cfg.split_batches,
            put_on_device=device_placement if device_placement is not None else self.device_placement,
            rng_types=self.rng_types,
            dispatch_batches=cfg.dispatch_batches,
            even_batches=cfg.even_batches,
            use_seedable_sampler=cfg.use_seedable_sampler,
            data_seed=cfg.data_seed,
            non_blocking=cfg.non_blocking,
            use_stateful_dataloader=cfg.use_stateful_dataloader,
            prefetch_size=cfg.prefetch_size,
            async_prefetch=cfg.async_prefetch,
            num_workers=cfg.num_workers,
        )
        dl.pipeline_stats = self.pipeline_stats
        self._dataloaders.append(dl)
        return dl

    def input_pipeline_metrics(self) -> dict:
        """Aggregated input-pipeline breakdown over every prepared loader:
        ``data_wait_ms`` (step loop blocked on data), ``stage_ms`` (collate +
        host→device), ``queue_depth``. Log it alongside loss — a rising
        ``data_wait_ms`` is MFU leaking to the host input path."""
        return self.pipeline_stats.summary()

    def serving_metrics(self) -> dict:
        """Aggregated serving-engine counters (TTFT, queue wait, decode
        tokens/sec, slot occupancy, batch efficiency) for every
        ``ServingEngine(accelerator=self)``; see
        ``serving.metrics.ServingStats.summary``."""
        return self.serving_stats.summary()

    def gateway_metrics(self) -> dict:
        """Aggregated HTTP-gateway counters (requests by status class,
        SSE streams, in-flight) for every
        ``ServingGateway(accelerator=self)``; see
        ``serving.metrics.GatewayStats.summary``."""
        return self.gateway_stats.summary()

    # ------------------------------------------------------------------
    # Gradient accumulation (reference: accelerator.py:1020-1090)
    # ------------------------------------------------------------------

    def _do_sync(self):
        if self.gradient_state.sync_with_dataloader and self.gradient_state.end_of_dataloader:
            self.step = 0
            self.gradient_state._set_sync_gradients(True)
        else:
            self.step += 1
            sync = (self.step % self.gradient_state.num_steps) == 0
            self.gradient_state._set_sync_gradients(sync or self.gradient_state.sync_each_batch)

    @contextlib.contextmanager
    def accumulate(self, *models):
        """Context marking one microbatch (reference: accumulate :1046).

        Unlike torch DDP there is no communication to skip — "not syncing"
        just means the optimizer defers its update.
        """
        self._do_sync()
        yield

    @contextlib.contextmanager
    def no_sync(self, model=None):
        """Parity context (reference: :931): forces accumulation for the block."""
        prev = self.gradient_state.sync_gradients
        self.gradient_state._set_sync_gradients(False)
        try:
            yield
        finally:
            self.gradient_state._set_sync_gradients(prev)

    @contextlib.contextmanager
    def join_uneven_inputs(self, joinables, even_batches=None):
        """Train/evaluate on uneven inputs (reference: :1091).

        Overrides ``even_batches`` on every prepared HOST-side map-style
        dataloader's batch sampler for the context's duration (reference
        behavior: :1136-1157), plus the config default for loaders prepared
        inside the context. Device-staged loaders are deliberately skipped
        (with a warning): their per-batch multi-host dispatch would deadlock
        on an uneven tail. ``joinables`` is accepted for API parity; there is no
        torch Join to wrap — gradient synchronization here happens inside
        compiled steps over global arrays, which REQUIRE every process to
        dispatch the same programs. The supported uneven pattern is
        therefore: iterate locally (per-process batch counts may differ —
        run no per-batch collectives), then aggregate once after the loop
        with ``gather_for_metrics(..., use_gather_object=True)`` /
        ``pad_across_processes``. Exercised by
        ``test_utils/scripts/test_script.py::check_uneven_tail`` in the
        real multi-process lane.
        """
        restore: list[tuple] = []
        prev_default = self.dataloader_config.even_batches
        n_loaders_at_entry = len(self._dataloaders)
        if even_batches is not None:
            restore.append((self.dataloader_config, prev_default))
            self.dataloader_config.even_batches = even_batches
            untoggleable = 0
            for dl in self._dataloaders:
                sampler = getattr(dl.base_dataloader, "batch_sampler", None)
                if hasattr(sampler, "even_batches") and not getattr(dl, "stage_to_device", False):
                    restore.append((sampler, sampler.even_batches))
                    sampler.even_batches = even_batches
                elif self.num_processes > 1:
                    # Device-staged loaders are NOT toggled: uneven tails mean
                    # per-process batch counts differ, and every device batch
                    # implies a multi-host dispatch all processes must join —
                    # toggling would trade padding for a distributed deadlock.
                    # Prepare the eval loader with device_placement=False to
                    # opt in (see the contract above). Dispatcher/iterable
                    # loaders have nothing to toggle (reference warns too,
                    # :1150-1155). Single-process loaders never pad, so the
                    # override is vacuously in effect for them.
                    untoggleable += 1
            if untoggleable:
                warnings.warn(
                    f"even_batches override skipped {untoggleable} prepared "
                    f"loader(s): device-staged loaders would deadlock on uneven "
                    f"tails (prepare with device_placement=False to opt in); "
                    f"dispatcher/iterable loaders have nothing to toggle."
                )
        try:
            yield
        finally:
            for obj, prev in restore:
                obj.even_batches = prev
            if even_batches is not None:
                # Loaders prepared INSIDE the context baked the override into
                # their samplers; restore them to the pre-context default so
                # the toggle really is scoped to the context's duration.
                for dl in self._dataloaders[n_loaders_at_entry:]:
                    sampler = getattr(dl.base_dataloader, "batch_sampler", None)
                    if hasattr(sampler, "even_batches"):
                        sampler.even_batches = prev_default

    # ------------------------------------------------------------------
    # backward (reference: accelerator.py:2164)
    # ------------------------------------------------------------------

    def next_rng_key(self):
        """Split and return a fresh PRNG key from the accelerator's stream."""
        self._rng_key, sub = jax.random.split(self._rng_key)
        return sub

    def _loss_fn_accepts_rng(self, loss_fn) -> bool:
        try:
            sig = inspect.signature(loss_fn)
            return len(sig.parameters) >= 3
        except (TypeError, ValueError):
            return False

    def backward(self, loss_fn: Callable, batch, model: Optional[AcceleratedModel] = None,
                 optimizer: Optional[AcceleratedOptimizer] = None, **kwargs):
        """Compute gradients of ``loss_fn(params, batch[, rng])`` and
        accumulate them (reference: backward :2164).

        * divides the loss by ``gradient_accumulation_steps`` (reference :2186)
        * applies the compute-dtype policy to params (autocast equivalent)
        * scales the loss under fp16 (reference: scaler.scale(loss).backward())
        * data-parallel reduction is implicit: the loss averages over the
          global sharded batch, XLA inserts the psum in the backward pass.

        Returns the (unscaled, fp32) loss value.
        """
        model = model or (self._models[0] if self._models else None)
        optimizer = optimizer or (self._optimizers[0] if self._optimizers else None)
        if model is None or optimizer is None:
            raise RuntimeError("backward() needs a prepared model and optimizer (call prepare first).")
        if optimizer._model is None:
            optimizer._model = model
        elif optimizer._model is not model:
            raise RuntimeError(
                "This optimizer is bound to a different model than the one passed to backward(). "
                "Pass matching model=/optimizer= arguments (prepare binds the k-th optimizer "
                "to the k-th model)."
            )
        if optimizer.opt_state is None:
            optimizer.init_state(model.params)

        # Key by the function object itself (prevents GC id-reuse; closures
        # with identical code but different captured values must NOT share a
        # compiled step) AND the accumulation count baked into it. The cache
        # is capped: passing a fresh lambda every step recompiles each time —
        # reuse one loss_fn object in hot loops.
        key = (loss_fn, self.gradient_state.num_steps)
        if key not in self._backward_cache:
            policy = self.policy
            accepts_rng = self._loss_fn_accepts_rng(loss_fn)
            num_steps = self.gradient_state.num_steps

            def compute_loss(params, batch, rng, scale):
                cparams = policy.cast_to_compute(params)
                out = loss_fn(cparams, batch, rng) if accepts_rng else loss_fn(cparams, batch)
                loss, aux = out if isinstance(out, tuple) else (out, None)
                raw_loss = loss
                if num_steps > 1:
                    loss = loss / num_steps
                if scale is not None:
                    loss = loss * scale.astype(loss.dtype)
                return loss.astype(jnp.float32), (raw_loss, aux)

            grad_fn = jax.value_and_grad(compute_loss, has_aux=True)

            @jax.jit
            def backward_step(params, batch, rng, scale):
                (_, (raw_loss, aux)), grads = grad_fn(params, batch, rng, scale)
                return raw_loss, aux, grads

            self._backward_cache_put(key, backward_step)

        scale = optimizer.loss_scale.scale if optimizer.loss_scale is not None else None
        raw_loss, aux, grads = self._backward_cache_get(key)(model.params, batch, self.next_rng_key(), scale)
        optimizer.accumulate_grads(grads)
        self._last_aux = aux
        return raw_loss

    def _backward_cache_put(self, key, step):
        """Insert a compiled backward step, evicting the LEAST RECENTLY USED
        entry at capacity (hits refresh recency via ``move_to_end``, so a hot
        loss_fn is never evicted by churn in rarely-used ones)."""
        if len(self._backward_cache) >= self._backward_cache_size:
            self._backward_cache.popitem(last=False)
        self._backward_cache[key] = step

    def _backward_cache_get(self, key):
        self._backward_cache.move_to_end(key)
        return self._backward_cache[key]

    # ------------------------------------------------------------------
    # Gradient clipping (reference: accelerator.py:2292)
    # ------------------------------------------------------------------

    @staticmethod
    @jax.jit
    def _clip_by_global_norm(grads, max_norm, inv_scale):
        """Unscale (fp16) + clip by global norm; jit-cached across calls."""
        grads = jax.tree_util.tree_map(
            lambda g: (g.astype(jnp.float32) * inv_scale).astype(g.dtype), grads
        )
        leaves = jax.tree_util.tree_leaves(grads)
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
        factor = jnp.minimum(1.0, max_norm / (gnorm + 1e-6))
        return jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads), gnorm

    def clip_grad_norm_(self, parameters=None, max_norm: float = 1.0, norm_type: float = 2.0):
        """Clip accumulated grads by global norm; returns the pre-clip norm of
        the first clipped optimizer (reference: clip_grad_norm_ :2292 —
        FSDP/XLA variants collapse into one jitted global-norm here, since
        grads are already global arrays). fp16 grads are unscaled first
        (reference: unscale_gradients :2264) and the optimizer is told not to
        unscale again at step()."""
        first_norm = None
        for opt in self._optimizers:
            if opt.acc_grads is None:
                continue
            if opt.loss_scale is not None and not opt._grads_already_unscaled:
                inv_scale = 1.0 / opt.loss_scale.scale
                opt._grads_already_unscaled = True
            else:
                inv_scale = jnp.asarray(1.0, jnp.float32)
            opt.acc_grads, gnorm = Accelerator._clip_by_global_norm(
                opt.acc_grads, jnp.asarray(max_norm, jnp.float32), inv_scale
            )
            if first_norm is None:
                first_norm = gnorm
        return first_norm

    def clip_grad_value_(self, parameters=None, clip_value: float = 1.0):
        """Clip accumulated grads elementwise (reference: :2344)."""
        for opt in self._optimizers:
            if opt.acc_grads is None:
                continue
            opt.acc_grads = jax.tree_util.tree_map(
                lambda g: jnp.clip(g, -clip_value, clip_value), opt.acc_grads
            )

    # ------------------------------------------------------------------
    # Fused train step (the fast path)
    # ------------------------------------------------------------------

    def compile_train_step(
        self,
        loss_fn: Callable,
        model: Optional[AcceleratedModel] = None,
        optimizer: Optional[AcceleratedOptimizer] = None,
        accumulation_steps: Optional[int] = None,
        max_grad_norm: Optional[float] = None,
        donate: bool = True,
        grad_reduce_dtype=None,
    ) -> Callable:
        """Build ONE jitted step: grads (+scan over microbatches), clip,
        optimizer update, loss-scale update — with buffer donation.

        If ``accumulation_steps > 1``, the step expects each batch leaf to
        have a leading ``[accumulation_steps, ...]`` microbatch dimension and
        runs a ``lax.scan`` over it (compiler-friendly accumulation — the
        GradientState bookkeeping the reference does in Python happens inside
        the executable).

        Returns ``step(batch) -> metrics`` operating on the bound model/
        optimizer state in-place.

        ``grad_reduce_dtype`` (e.g. ``jnp.bfloat16``) differentiates with
        respect to the compute-cast parameters so gradients — and therefore
        the implicit cross-replica all-reduce GSPMD inserts over the dp
        axis — stay in that dtype, halving gradient communication volume
        vs fp32 (the reference's DDP ``bf16_compress_hook``,
        examples/by_feature/ddp_comm_hook.py; there it compresses the
        bucket, here the reduction itself runs narrow). Gradients are
        upcast to fp32 AFTER the reduction for clipping/optimizer. The
        cross-replica sum runs in the narrow dtype — the same accuracy
        trade the torch hook makes; leave None for fp32 reductions.

        Because the step differentiates with respect to the cast params,
        ``grad_reduce_dtype`` is also the FORWARD compute dtype when it
        differs from the mixed-precision policy's (e.g.
        ``mixed_precision='no'`` with ``grad_reduce_dtype=bf16`` runs the
        forward in bf16, a wider accuracy change than the torch hook's
        communication-only compression) — a warning is emitted for such
        mismatches. With matching dtypes (bf16/bf16, fp16/fp16) it is
        communication-narrowing only.

        With ``fsdp_plugin.activation_checkpointing=True`` the whole loss
        computation is rematerialized (``jax.checkpoint`` with the
        dots-saveable policy) regardless of any model-level remat config
        (reference: accelerator.py:1485-1499 applies FSDP activation
        checkpointing to the wrapped module). With
        ``fsdp_plugin.cpu_offload=True`` the step is split into a grad
        executable (no optimizer state resident) and an update executable
        (no activations live), with the state streamed from/to pinned host
        memory at the boundary (parallel/host_offload.py).
        """
        model = model or self._models[0]
        optimizer = optimizer or self._optimizers[0]
        if optimizer._model is None:
            optimizer._model = model
        if optimizer.opt_state is None:
            optimizer.init_state(model.params)
        accum = accumulation_steps if accumulation_steps is not None else self.gradient_state.num_steps
        policy = self.policy
        if (grad_reduce_dtype is not None
                and jnp.dtype(grad_reduce_dtype) != jnp.dtype(policy.compute_dtype)):
            warnings.warn(
                f"grad_reduce_dtype={jnp.dtype(grad_reduce_dtype).name} differs from the "
                f"mixed-precision compute dtype {jnp.dtype(policy.compute_dtype).name}: the "
                "forward will also run in the reduce dtype (the step differentiates w.r.t. "
                "the cast params), which changes accuracy beyond communication narrowing. "
                "Match the dtypes to narrow only the gradient all-reduce.",
                stacklevel=2,
            )
        accepts_rng = self._loss_fn_accepts_rng(loss_fn)
        tx = optimizer.tx
        has_scale = optimizer.loss_scale is not None
        scaler_kwargs = optimizer.scaler_kwargs
        fsdp = self.state.fsdp_plugin
        remat_loss = bool(fsdp is not None and fsdp.activation_checkpointing)
        offload = optimizer.offload_to_host
        from .ops.quant import fp8_meta_mask, has_fp8_meta

        fp8_mask = fp8_meta_mask(model.params) if has_fp8_meta(model.params) else None

        def loss_and_grads(params, microbatch, rng, scale):
            def compute(p):
                cp = p if grad_reduce_dtype is not None else policy.cast_to_compute(p)
                out = loss_fn(cp, microbatch, rng) if accepts_rng else loss_fn(cp, microbatch)
                loss, aux = out if isinstance(out, tuple) else (out, None)
                scaled = loss / accum
                if scale is not None:
                    scaled = scaled * scale.astype(scaled.dtype)
                return scaled.astype(jnp.float32), loss

            if remat_loss:
                from .parallel.sharding import resolve_remat_policy

                compute = jax.checkpoint(
                    compute, policy=resolve_remat_policy(fsdp.remat_policy)
                )
            if grad_reduce_dtype is not None:
                # Differentiate w.r.t. the CAST params: cotangents (and the
                # implicit dp all-reduce) stay in the narrow dtype; upcast
                # only after, for clipping/optimizer.
                from .precision import _cast_floating

                cp0 = _cast_floating(policy.cast_to_compute(params), grad_reduce_dtype)
                (scaled, loss), grads = jax.value_and_grad(compute, has_aux=True)(cp0)
                grads = jax.tree_util.tree_map(
                    lambda g, p: (g.astype(p.dtype)
                                  if jnp.issubdtype(p.dtype, jnp.floating) else g),
                    grads, params)
                return loss, grads
            (scaled, loss), grads = jax.value_and_grad(compute, has_aux=True)(params)
            return loss, grads

        def grad_phase(params, loss_scale, batch, rng):
            scale = loss_scale.scale if has_scale else None
            if accum > 1:
                def scan_body(carry, microbatch):
                    acc_grads, loss_sum, i = carry
                    sub = jax.random.fold_in(rng, i)
                    loss, grads = loss_and_grads(params, microbatch, sub, scale)
                    acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, grads)
                    return (acc_grads, loss_sum + loss, i + 1), None

                zero_grads = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
                (grads, loss_sum, _), _ = jax.lax.scan(
                    scan_body, (zero_grads, jnp.zeros((), jnp.float32), 0), batch
                )
                loss = loss_sum / accum
            else:
                loss, grads = loss_and_grads(params, batch, rng, scale)
            return grads, loss

        # ZeRO (optimizer.zero_sharding): the update pins its outputs with
        # sharding constraints. The constraint on params is load-bearing:
        # without it GSPMD propagates the moments' dp sharding onto the
        # updated params, breaking the donation alias; with it the update
        # lowers to reduce-scatter(grads) -> shard-local Adam ->
        # all-gather(params), and per-replica opt-state bytes are 1/dp.
        # (Constraints inside the traced function, not jit in/out_shardings:
        # explicitly-sharded jits segfault after a persistent-compile-cache
        # round-trip on the CPU backend, and the inputs are already committed
        # to these layouts at init_state time.)
        zero_sh = optimizer.opt_state_shardings
        zero_p_sh = None
        if zero_sh is not None:
            zero_p_sh = model.param_shardings
            if zero_p_sh is None:
                repl = replicated_sharding(self.mesh)
                zero_p_sh = jax.tree_util.tree_map(lambda _: repl, model.params)

        def update_phase(params, opt_state, loss_scale, grads, loss):
            import optax

            if has_scale:
                from .precision import grads_finite, unscale_grads, update_loss_scale

                grads = unscale_grads(grads, loss_scale)
                finite = grads_finite(grads)
            else:
                finite = jnp.asarray(True)

            gnorm = None
            if max_grad_norm is not None:
                # fp8 statistics leaves carry updated amax/scale values in
                # their "gradients" (ops/quant.py): they must neither enter
                # the norm nor be scaled by the clip factor.
                if fp8_mask is not None:
                    leaves = [
                        g
                        for g, is_meta in zip(
                            jax.tree_util.tree_leaves(grads),
                            jax.tree_util.tree_leaves(fp8_mask),
                        )
                        if not is_meta
                    ]
                else:
                    leaves = jax.tree_util.tree_leaves(grads)
                gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves))
                factor = jnp.minimum(1.0, max_grad_norm / (gnorm + 1e-6))
                if fp8_mask is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g, is_meta: g if is_meta else (g * factor).astype(g.dtype),
                        grads,
                        fp8_mask,
                    )
                else:
                    grads = jax.tree_util.tree_map(lambda g: (g * factor).astype(g.dtype), grads)

            updates, new_opt_state = tx.update(grads, opt_state, params)
            new_params = optax.apply_updates(params, updates)
            if has_scale:
                from .precision import update_loss_scale as _uls

                new_params = jax.tree_util.tree_map(lambda n, o: jnp.where(finite, n, o), new_params, params)
                new_opt_state = jax.tree_util.tree_map(
                    lambda n, o: jnp.where(finite, n, o) if hasattr(n, "dtype") else n, new_opt_state, opt_state
                )
                new_scale = _uls(loss_scale, finite, scaler_kwargs)
            else:
                new_scale = loss_scale

            metrics = {"loss": loss.astype(jnp.float32)}
            if gnorm is not None:
                metrics["grad_norm"] = gnorm
            if has_scale:
                metrics["loss_scale"] = new_scale.scale
                metrics["finite"] = finite
            if zero_sh is not None:
                new_params = jax.lax.with_sharding_constraint(new_params, zero_p_sh)
                new_opt_state = jax.lax.with_sharding_constraint(new_opt_state, zero_sh)
            return new_params, new_opt_state, new_scale, metrics

        def train_step(params, opt_state, loss_scale, batch, rng):
            grads, loss = grad_phase(params, loss_scale, batch, rng)
            return update_phase(params, opt_state, loss_scale, grads, loss)

        def _check_accum_shape(batch):
            if accum > 1:
                bad = [
                    np.shape(leaf)
                    for leaf in jax.tree_util.tree_leaves(batch)
                    if np.ndim(leaf) == 0 or np.shape(leaf)[0] != accum
                ]
                if bad:
                    raise ValueError(
                        f"compile_train_step(accumulation_steps={accum}) expects every batch "
                        f"leaf to have a leading microbatch dim of {accum}; got leading dims "
                        f"{[s[0] if s else None for s in bad]}. Reshape to [accum, micro, ...]."
                    )

        def _record(metrics):
            if has_scale:
                # Don't sync here: record the device-side finite flag; the
                # steps_applied/step_was_skipped properties drain it lazily.
                optimizer._pending_finite.append(metrics["finite"])
                optimizer._last_finite = metrics["finite"]
            else:
                optimizer._steps_applied += 1
            return metrics

        # ZeRO steps stay out of the persistent compile cache on the CPU
        # backend (sharding.py zero_step_compile_cache_guard). The in-memory
        # jit cache still holds the executable after the first call, so only
        # compiles (first call and any new batch shape) pay the toggle.
        _zero_nocache = zero_sh is not None and jax.default_backend() == "cpu"

        def _call_uncached(fn, *args):
            from .parallel.sharding import zero_step_compile_cache_guard

            with zero_step_compile_cache_guard(_zero_nocache):
                return fn(*args)

        if not offload:
            jitted = jax.jit(train_step, donate_argnums=(0, 1) if donate else ())

            def step(batch):
                _check_accum_shape(batch)
                rng = self.next_rng_key()
                new_params, new_opt_state, new_scale, metrics = _call_uncached(
                    jitted, model.params, optimizer.opt_state, optimizer.loss_scale, batch, rng
                )
                model.params = new_params
                optimizer.opt_state = new_opt_state
                optimizer.loss_scale = new_scale
                return _record(metrics)

            step._jitted = jitted  # expose for AOT/benchmark introspection
            return step

        # Host-offloaded optimizer state: two executables. The grad phase
        # never sees the optimizer state, so HBM peaks at params +
        # activations + grads; the update phase holds params + grads + state
        # but no activations. Grads are donated into the update.
        from .parallel.host_offload import to_device, to_host

        jitted_grads = jax.jit(grad_phase)
        jitted_update = jax.jit(
            update_phase, donate_argnums=(0, 1, 3) if donate else ()
        )

        def step(batch):
            _check_accum_shape(batch)
            rng = self.next_rng_key()
            grads, loss = jitted_grads(model.params, optimizer.loss_scale, batch, rng)
            opt_in = to_device(optimizer.opt_state, self.mesh)
            new_params, new_opt_state, new_scale, metrics = _call_uncached(
                jitted_update, model.params, opt_in, optimizer.loss_scale, grads, loss
            )
            model.params = new_params
            optimizer.opt_state = to_host(new_opt_state, self.mesh)
            optimizer.loss_scale = new_scale
            return _record(metrics)

        step._jitted = jitted_update  # expose for AOT/benchmark introspection
        step._jitted_grads = jitted_grads
        return step

    # ------------------------------------------------------------------
    # Collectives / metrics (reference: accelerator.py:2360-2479)
    # ------------------------------------------------------------------

    def gather(self, tensor):
        """Gather a pytree across processes, concatenated on dim 0 (reference: :2378)."""
        return gather(tensor)

    def gather_for_metrics(self, input_data, use_gather_object: bool = False):
        """Gather, dropping duplicate tail samples added for even batching
        (reference: gather_for_metrics :2408 using GradientState.remainder)."""
        try:
            recursively_apply(lambda x: x, input_data, error_on_other_type=True)
            all_tensors = True
        except TypeError:
            all_tensors = False

        if use_gather_object or not all_tensors:
            data = gather_object(input_data)
        else:
            data = self.gather(input_data)

        if self.gradient_state.end_of_dataloader and self.gradient_state.remainder > 0:
            remainder = self.gradient_state.remainder

            def _adjust_samples(tensor):
                # Gathered objects may be ragged lists (np.ndim would choke
                # converting them); arrays slice on their batch dim, 0-d
                # scalars pass through (the remainder describes a batch dim
                # they don't have).
                if isinstance(tensor, (list, tuple)):
                    return tensor[:remainder]
                if getattr(tensor, "ndim", 0) == 0:
                    return tensor
                return tensor[:remainder]

            if use_gather_object or not all_tensors:
                return _adjust_samples(data)
            return recursively_apply(_adjust_samples, data)
        return data

    def reduce(self, tensor, reduction="sum", scale=1.0):
        """Reduce a pytree across processes (sum/mean, reference: :2517)."""
        return reduce(tensor, reduction, scale)

    def pad_across_processes(self, tensor, dim=0, pad_index=0, pad_first=False):
        """Pad each process's tensor to the max length before gathering ragged data (reference: :2467)."""
        return pad_across_processes(tensor, dim=dim, pad_index=pad_index, pad_first=pad_first)

    def unwrap_model(self, model, keep_fp32_wrapper: bool = True):
        """Return the inner Model (reference: unwrap_model delegates to
        extract_model_from_parallel — same layering here)."""
        from .utils.other import extract_model_from_parallel

        return extract_model_from_parallel(model, keep_fp32_wrapper=keep_fp32_wrapper)

    def get_state_dict(self, model, unwrap: bool = True):
        """Full (host-gathered) parameter pytree (reference: :3291 — the
        ZeRO-3 consolidation equivalent is fetching the addressable global
        arrays)."""
        params = model.params if isinstance(model, AcceleratedModel) else model
        return jax.tree_util.tree_map(lambda x: np.asarray(jax.device_get(x)), params)

    # ------------------------------------------------------------------
    # Cross-process trigger (reference: accelerator.py:2198-2255)
    # ------------------------------------------------------------------

    def set_trigger(self):
        self.flag_tensor = True

    def check_trigger(self) -> bool:
        """True if ANY process called set_trigger (early stopping, NaN
        breakpoints)."""
        flag = np.array([1 if self.flag_tensor else 0], dtype=np.int64)
        if self.num_processes > 1:
            from jax.experimental import multihost_utils

            total = int(multihost_utils.process_allgather(flag, tiled=False).sum())
        else:
            total = int(flag[0])
        if total > 0:
            self.flag_tensor = None
            return True
        return False

    # ------------------------------------------------------------------
    # Preemption (graceful save-and-restart; completes the elastic story
    # with `accelerate-tpu launch --max_restarts` + auto-resume)
    # ------------------------------------------------------------------

    #: exit code signalling "preempted after saving" — launchers and pod
    #: schedulers treat nonzero as restart-eligible; 75 is EX_TEMPFAIL.
    PREEMPTED_EXIT_CODE = 75

    def install_preemption_handler(self, signals=None):
        """Catch SIGTERM (the preemption notice on TPU pods and most
        schedulers) and latch :attr:`preemption_requested`. The training
        loop checks it at step boundaries and winds down::

            accelerator.install_preemption_handler()
            for batch in loader:
                if accelerator.preemption_requested:
                    accelerator.save_state()
                    sys.exit(accelerator.PREEMPTED_EXIT_CODE)
                step(batch)

        ``launch --max_restarts`` (or the pod scheduler) then relaunches,
        and ``load_state()`` resumes from the just-saved checkpoint. The
        reference delegates this to torch elastic's restart-the-world
        (reference: commands/launch.py:775-799); the handler only sets a
        flag, so a signal mid-XLA-dispatch is safe."""
        import signal as _signal

        self._preemption_requested = False
        for sig in signals or (_signal.SIGTERM,):
            _signal.signal(sig, self._on_preemption_signal)

    def _on_preemption_signal(self, signum, frame):
        self._preemption_requested = True

    @property
    def preemption_requested(self) -> bool:
        """True once a preemption signal arrived (see
        :meth:`install_preemption_handler`)."""
        return getattr(self, "_preemption_requested", False)

    # ------------------------------------------------------------------
    # Autocast / profile (reference: accelerator.py:3383, 3423)
    # ------------------------------------------------------------------

    @contextlib.contextmanager
    def autocast(self, autocast_handler: Optional[AutocastKwargs] = None):
        """Parity context. In JAX the dtype policy is baked into compiled
        fns; this context exposes the active policy for manual use."""
        yield self.policy

    def profile(self, profile_handler: Optional[ProfileKwargs] = None):
        """Context manager capturing a jax.profiler trace (reference: :3423).

        Trace directory precedence: the handler's ``output_trace_dir`` (the
        user's explicit choice), then the project's ``logging_dir``, then
        ``./jax_trace``.
        """
        handler = profile_handler or self.profile_handler or ProfileKwargs()
        log_dir = (handler.output_trace_dir
                   or self.project_configuration.logging_dir or "./jax_trace")
        # The device trace and the host-side breakdowns (input pipeline,
        # serving engine) tell one story; sessions built here snapshot
        # data_wait/stage and serving counters per step().
        return (handler.build(log_dir=log_dir)
                .attach_pipeline_stats(self.pipeline_stats)
                .attach_serving_stats(self.serving_stats)
                .attach_gateway_stats(self.gateway_stats))

    # ------------------------------------------------------------------
    # Memory / lifecycle (reference: accelerator.py:3219-3270)
    # ------------------------------------------------------------------

    def free_memory(self, *objects):
        """Drop every prepared-object reference and free device buffers (reference: :3219)."""
        from .utils.memory import release_memory

        self._models.clear()
        self._optimizers.clear()
        self._schedulers.clear()
        self._dataloaders.clear()
        self._backward_cache.clear()
        self._fused_cache.clear()
        self.step = 0
        return release_memory(*objects)

    def clear(self, *objects):
        """Alias of free_memory (reference: :3270)."""
        return self.free_memory(*objects)

    def register_for_checkpointing(self, *objects):
        """Track custom stateful objects for save_state/load_state
        (reference: :3349)."""
        invalid = [o for o in objects if not (hasattr(o, "state_dict") and hasattr(o, "load_state_dict"))]
        if invalid:
            raise ValueError(
                f"All `objects` must have `state_dict`/`load_state_dict`: got invalid {invalid}"
            )
        self._custom_objects.extend(objects)

    def skip_first_batches(self, dataloader, num_batches: int = 0):
        """Fast-forward a prepared loader for mid-epoch resume (reference: :3440)."""
        return skip_first_batches(dataloader, num_batches)

    # save_state/load_state live in checkpointing.py and are bound here to
    # keep this module focused.
    def save_state(self, output_dir: Optional[str] = None, **save_model_kwargs):
        """Checkpoint params/optimizer/RNG/loaders/custom objects (reference: :2915).

        Pass ``blocking=False`` for an async checkpoint: arrays are
        snapshotted to host synchronously, the filesystem write streams in
        the background, and training continues. Durability points:
        :meth:`wait_for_checkpoint`, the next save/load, or process exit."""
        from .checkpointing import save_accelerator_state

        return save_accelerator_state(self, output_dir, **save_model_kwargs)

    def wait_for_checkpoint(self):
        """Block until every in-flight async ``save_state(blocking=False)``
        is durable on disk."""
        from .checkpointing import wait_for_saves

        wait_for_saves()

    def load_state(self, input_dir: Optional[str] = None, **load_model_kwargs):
        """Restore a save_state checkpoint, resharding on topology changes (reference: :3081)."""
        from .checkpointing import load_accelerator_state

        return load_accelerator_state(self, input_dir, **load_model_kwargs)

    def save_model(self, model, save_directory: str, max_shard_size: str = "10GB", safe_serialization: bool = True):
        """Export params as (sharded) safetensors for serving (reference: :2848)."""
        from .checkpointing import save_model as _save_model

        return _save_model(self, model, save_directory, max_shard_size, safe_serialization)

    # Tracking API (tracking.py) ----------------------------------------
    def init_trackers(self, project_name: str, config: Optional[dict] = None, init_kwargs: Optional[dict] = None):
        """Start every configured experiment tracker (reference: :2568)."""
        from .tracking import filter_trackers, resolve_trackers

        self.trackers = resolve_trackers(
            getattr(self, "_log_with", None), project_name, self.project_configuration.logging_dir,
            config=config, init_kwargs=init_kwargs or {},
        )

    def log(self, values: dict, step: Optional[int] = None, log_kwargs: Optional[dict] = None,
            include_input_pipeline: bool = False, include_serving: bool = False,
            include_gateway: bool = False):
        """Log scalars to every active tracker, main process only (reference: :2625).

        ``include_input_pipeline=True`` merges the aggregated loader
        breakdown (``input_pipeline/data_wait_ms`` etc.) into the payload;
        ``include_serving=True`` does the same for serving-engine counters
        (``serving/ttft_ms`` etc.), and ``include_gateway=True`` for the
        HTTP gateway's counters (``gateway/http_requests`` etc.)."""
        if include_input_pipeline:
            from .tracking import with_input_pipeline_metrics

            values = with_input_pipeline_metrics(values, self.pipeline_stats)
        if include_serving:
            from .tracking import with_serving_metrics

            values = with_serving_metrics(values, self.serving_stats)
        if include_gateway:
            from .tracking import with_gateway_metrics

            values = with_gateway_metrics(values, self.gateway_stats)
        for tracker in self.trackers:
            tracker.log(values, step=step, **((log_kwargs or {}).get(tracker.name, {})))

    def get_tracker(self, name: str, unwrap: bool = False):
        """Fetch one active tracker by name; ``unwrap`` returns the raw client run."""
        for tracker in self.trackers:
            if tracker.name == name:
                return tracker.tracker if unwrap else tracker
        raise ValueError(f"{name} is not an available tracker: {[t.name for t in self.trackers]}")

    def end_training(self):
        """Drain in-flight async checkpoint saves, then flush/close all
        trackers and barrier (reference: :2645). The save drain comes first:
        a script that calls ``end_training()`` and exits must not drop an
        Orbax write that is still in flight."""
        from . import checkpointing

        checkpointing.wait_for_saves()
        for tracker in self.trackers:
            tracker.finish()
        self.wait_for_everyone()
