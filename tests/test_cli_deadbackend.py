"""Every CLI subcommand must complete when the accelerator backend is dead.

The round-1 hang class: a PJRT plugin whose transport is down blocks
forever inside backend initialization, and any in-process device query
(even an incidental PRNGKey) wedges the command. This lane simulates that
world with a sitecustomize that makes non-CPU backend creation hang, then
drives each subcommand end-to-end under a hard subprocess timeout.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SITECUSTOMIZE = textwrap.dedent(
    """
    # Injected by tests/test_cli_deadbackend.py: simulate a dead accelerator
    # transport — creating any backend WITHOUT an explicit cpu pin blocks
    # forever (like a PJRT plugin dialing a down tunnel). A cpu pin
    # (jax.config or JAX_PLATFORMS env) passes through, because a pinned-CPU
    # process never touches the dead transport.
    import os

    if os.environ.get("ATPU_TEST_DEAD_BACKEND"):
        import jax
        from jax._src import xla_bridge

        _orig_backends = xla_bridge.backends

        def _backends(*a, **k):
            plats = (
                getattr(jax.config, "jax_platforms", None)
                or os.environ.get("JAX_PLATFORMS")
                or ""
            )
            if plats.split(",")[0].strip().lower() == "cpu":
                return _orig_backends(*a, **k)
            import time

            time.sleep(3600)

        xla_bridge.backends = _backends
    """
)


@pytest.fixture
def dead_env(tmp_path):
    """Env for CLI children: dead backend, no platform pin, fast probes."""
    site_dir = tmp_path / "site"
    site_dir.mkdir()
    (site_dir / "sitecustomize.py").write_text(SITECUSTOMIZE)
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)            # simulate an unpinned user shell
    env.pop("ACCELERATE_TPU_PLATFORM", None)
    env["ATPU_TEST_DEAD_BACKEND"] = "1"
    env["PYTHONPATH"] = f"{site_dir}:{REPO}:" + env.get("PYTHONPATH", "")
    env["ACCELERATE_TPU_PROBE_TIMEOUT"] = "5"  # don't pay 60-90s per probe
    env["ACCELERATE_TPU_PROBE_CACHE"] = str(tmp_path / "probe.json")
    env["ACCELERATE_TPU_CONFIG_DIR"] = str(tmp_path / "cfg")
    return env


def _run(argv, env, timeout=90):
    return subprocess.run(
        [sys.executable, "-m", "accelerate_tpu.commands.accelerate_cli", *argv],
        capture_output=True, text=True, env=env, timeout=timeout, cwd=REPO,
    )


def test_sitecustomize_simulation_hangs_unpinned(dead_env):
    """Sanity: the simulation really does hang an unpinned device query."""
    with pytest.raises(subprocess.TimeoutExpired):
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            capture_output=True, env=dead_env, timeout=10,
        )


def test_env_completes_and_reports_fallback(dead_env):
    r = _run(["env"], dead_env)
    assert r.returncode == 0, r.stderr
    assert "cpu" in r.stdout.lower()


def test_estimate_memory_completes(dead_env):
    r = _run(["estimate-memory", "llama-tiny", "--dtypes", "bfloat16"], dead_env)
    assert r.returncode == 0, r.stderr
    assert "bfloat16" in r.stdout


def test_config_default_completes(dead_env):
    r = _run(["config", "--default"], dead_env)
    assert r.returncode == 0, r.stderr
    assert os.path.exists(os.path.join(dead_env["ACCELERATE_TPU_CONFIG_DIR"],
                                       "default_config.yaml"))


def test_merge_weights_completes(dead_env, tmp_path):
    from safetensors.numpy import save_file

    src = tmp_path / "ckpt"
    src.mkdir()
    save_file({"w": np.ones((4, 4), np.float32)}, str(src / "model.safetensors"))
    out = tmp_path / "merged.safetensors"
    r = _run(["merge-weights", str(src), str(out)], dead_env)
    assert r.returncode == 0, r.stderr
    assert out.exists()


def test_launch_trivial_script_completes(dead_env, tmp_path):
    script = tmp_path / "noop.py"
    script.write_text("print('LAUNCHED_OK')\n")
    r = _run(["launch", str(script)], dead_env)
    assert r.returncode == 0, r.stderr
    assert "LAUNCHED_OK" in r.stdout


def test_probe_file_cache_spares_second_invocation(dead_env):
    """The first command pays the (shortened) probe; the second reads the
    cross-process cache file instead of probing again."""
    _run(["env"], dead_env)
    cache = dead_env["ACCELERATE_TPU_PROBE_CACHE"]
    assert os.path.exists(cache)
    rec = json.load(open(cache))
    assert rec["result"] is None               # dead backend was recorded
    mtime = os.path.getmtime(cache)
    r = _run(["env"], dead_env)
    assert r.returncode == 0
    # A re-probe would rewrite the cache file; a cache hit leaves it alone.
    assert os.path.getmtime(cache) == mtime
