"""Context-parallel attention correctness on the 8-device CPU mesh.

Net-new capability (SURVEY.md §5: the reference has no ring attention /
context parallelism); exactness is checked against the full einsum
attention, forward and backward, causal and bidirectional.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from accelerate_tpu import AcceleratorState, MeshConfig
from accelerate_tpu.ops.attention import _einsum_attention
from accelerate_tpu.ops.ring_attention import (
    context_parallel_attention,
    ring_attention,
    ulysses_attention,
)


def cp_mesh(cp=8):
    return MeshConfig(dp=1, cp=cp).build()


def make_qkv(B=2, S=64, H=8, D=16, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in keys)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_cp_attention_matches_full(fn, causal):
    mesh = cp_mesh()
    q, k, v = make_qkv()
    ref = _einsum_attention(q, k, v, causal=causal)
    out = fn(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_cp_attention_grads_match(fn):
    mesh = cp_mesh()
    q, k, v = make_qkv()

    def loss_full(q, k, v):
        return (_einsum_attention(q, k, v, causal=True) ** 2).sum()

    def loss_cp(q, k, v):
        return (fn(q, k, v, mesh=mesh, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_cp = jax.grad(loss_cp, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_cp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("inner_chunk", [
    pytest.param(4, marks=pytest.mark.nightly), 8,
    pytest.param(16, marks=pytest.mark.nightly),
])
def test_ring_attention_sub_chunked_inner_matches_full(causal, inner_chunk):
    """The inner sub-chunking (logits tile bounded at [.., S_local, inner])
    must stay exact for every tile/boundary alignment, incl. grads."""
    mesh = cp_mesh(cp=4)  # remaining devices absorb into dp=2: B must divide
    q, k, v = make_qkv(B=2, S=64, H=2, D=8, seed=1)  # S_local=16 > inner_chunk
    ref = _einsum_attention(q, k, v, causal=causal)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal, inner_chunk=inner_chunk)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    def loss_full(q, k, v):
        return (_einsum_attention(q, k, v, causal=causal) ** 2).sum()

    def loss_cp(q, k, v):
        return (ring_attention(q, k, v, mesh=mesh, causal=causal,
                               inner_chunk=inner_chunk) ** 2).sum()

    g_ref = jax.grad(loss_full, argnums=(0, 1, 2))(q, k, v)
    g_cp = jax.grad(loss_cp, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_cp):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=5e-5, rtol=5e-5)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("strategy", ["ring", "ulysses"])
def test_cp_attention_gqa_unrepeated_kv_matches_expanded(strategy, causal):
    """GQA KV enters the CP strategies UNREPEATED (G-wide over the wire —
    H/G times less ICI traffic); results must equal attention over
    explicitly expanded KV."""
    mesh = MeshConfig(dp=1, cp=2, devices=jax.devices()[:2]).build()  # kv=2 % cp=2 == 0
    keys = jax.random.split(jax.random.PRNGKey(7), 3)
    q = jax.random.normal(keys[0], (2, 64, 8, 16), jnp.float32)
    k = jax.random.normal(keys[1], (2, 64, 2, 16), jnp.float32)
    v = jax.random.normal(keys[2], (2, 64, 2, 16), jnp.float32)
    k_full = jnp.repeat(k, 4, axis=2)
    v_full = jnp.repeat(v, 4, axis=2)
    ref = _einsum_attention(q, k_full, v_full, causal=causal)
    fn = ring_attention if strategy == "ring" else ulysses_attention
    out = fn(q, k, v, mesh=mesh, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gqa_grads_match_expanded():
    mesh = MeshConfig(dp=1, cp=2, devices=jax.devices()[:2]).build()
    keys = jax.random.split(jax.random.PRNGKey(8), 3)
    q = jax.random.normal(keys[0], (2, 32, 4, 8), jnp.float32)
    k = jax.random.normal(keys[1], (2, 32, 2, 8), jnp.float32)
    v = jax.random.normal(keys[2], (2, 32, 2, 8), jnp.float32)

    def loss_ref(q, k, v):
        kf, vf = jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2)
        return (_einsum_attention(q, kf, vf, causal=True) ** 2).sum()

    def loss_ring(q, k, v):
        return (ring_attention(q, k, v, mesh=mesh, causal=True) ** 2).sum()

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b, nm in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5, rtol=5e-5,
                                   err_msg=f"d{nm}")


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_cp_gqa_trivial_axis_fallback_expands(fn):
    """axis_size==1: the dense fallback needs equal heads — unrepeated GQA
    KV must be expanded, not crash."""
    mesh = MeshConfig(dp=1, cp=1, devices=jax.devices()[:1]).build()
    keys = jax.random.split(jax.random.PRNGKey(9), 3)
    q = jax.random.normal(keys[0], (1, 32, 4, 8), jnp.float32)
    k = jax.random.normal(keys[1], (1, 32, 2, 8), jnp.float32)
    v = jax.random.normal(keys[2], (1, 32, 2, 8), jnp.float32)
    ref = _einsum_attention(q, jnp.repeat(k, 2, axis=2), jnp.repeat(v, 2, axis=2),
                            causal=True)
    out = fn(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_gqa_kv_unshardable_over_tp_expands():
    """tp axis that cannot split G kv heads: the entry expands KV (the
    pre-unrepeated behavior) instead of failing in shard_map."""
    mesh = MeshConfig(dp=1, cp=2, tp=4, devices=jax.devices()).build()
    keys = jax.random.split(jax.random.PRNGKey(10), 3)
    q = jax.random.normal(keys[0], (1, 32, 8, 8), jnp.float32)
    k = jax.random.normal(keys[1], (1, 32, 2, 8), jnp.float32)  # 2 % tp=4 != 0
    v = jax.random.normal(keys[2], (1, 32, 2, 8), jnp.float32)
    ref = _einsum_attention(q, jnp.repeat(k, 4, axis=2), jnp.repeat(v, 4, axis=2),
                            causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_inner_chunk_reads_context_parallel_plugin():
    """inner_chunk=None resolves from ContextParallelPlugin.ring_inner_chunk
    (the framework-wide knob) and stays exact."""
    from unittest import mock

    import importlib

    from accelerate_tpu import AcceleratorState
    from accelerate_tpu.utils.dataclasses import ContextParallelPlugin

    # ops/__init__ re-exports the same-named function over the submodule
    # attribute; resolve the module itself for patching.
    ra = importlib.import_module("accelerate_tpu.ops.ring_attention")

    AcceleratorState(cp_plugin=ContextParallelPlugin(cp_size=4, ring_inner_chunk=8))
    mesh = cp_mesh(cp=4)
    q, k, v = make_qkv(B=2, S=64, H=2, D=8, seed=4)
    seen = {}
    real = ra._ring_fn

    def spy(mesh_, axis, size, causal, inner):
        seen["inner"] = inner
        return real(mesh_, axis, size, causal, inner)

    with mock.patch.object(ra, "_ring_fn", side_effect=spy):
        out = ra.ring_attention(q, k, v, mesh=mesh, causal=True)
    assert seen["inner"] == 8
    ref = _einsum_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_indivisible_inner_chunk_falls_back():
    """inner_chunk not dividing S_local: whole-block path, still exact."""
    mesh = cp_mesh(cp=4)
    q, k, v = make_qkv(B=2, S=64, H=2, D=8, seed=2)  # S_local=16, inner 5 -> fallback
    ref = _einsum_attention(q, k, v, causal=True)
    out = ring_attention(q, k, v, mesh=mesh, causal=True, inner_chunk=5)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_ring_attention_under_jit_with_sharded_inputs():
    """Ring attention composes with jit + seq-sharded global arrays."""
    mesh = cp_mesh()
    q, k, v = make_qkv()
    sharding = NamedSharding(mesh, P(None, "cp", None, None))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh=mesh, causal=True))(qs, ks, vs)
    ref = _einsum_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_auto_strategy_selection():
    mesh = cp_mesh()
    q, k, v = make_qkv(H=8)  # divisible by 8 -> ulysses
    ref = _einsum_attention(q, k, v, causal=True)
    out = context_parallel_attention(q, k, v, mesh=mesh, strategy="auto")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # 4 heads on an 8-way axis -> must route to ring (ulysses would raise)
    q4, k4, v4 = make_qkv(H=4, D=16)
    ref4 = _einsum_attention(q4, k4, v4, causal=True)
    out4 = context_parallel_attention(q4, k4, v4, mesh=mesh, strategy="auto")
    np.testing.assert_allclose(np.asarray(out4), np.asarray(ref4), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("fn", [ring_attention, ulysses_attention])
def test_cp_composes_with_dp_and_tp(fn):
    """dp x cp x tp mesh: batch stays dp-sharded and heads tp-sharded through
    the shard_map boundary; result still exact."""
    mesh = MeshConfig(dp=2, cp=2, tp=2).build()
    q, k, v = make_qkv(B=4, S=32, H=8, D=16)
    ref = _einsum_attention(q, k, v, causal=True)
    out = fn(q, k, v, mesh=mesh, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_explicit_backend_raises_on_bad_shapes():
    """Explicit ring on a cp>1 mesh with a non-shardable seq len must raise,
    not silently fall back (memory asymptotics)."""
    from accelerate_tpu.models.llama import multi_head_attention

    AcceleratorState._reset_state()
    AcceleratorState(mesh_config=MeshConfig(dp=1, cp=8))
    q, k, v = make_qkv(S=60)
    with pytest.raises(ValueError, match="not divisible"):
        multi_head_attention(q, k, v, backend="ring")
    # 'auto' with the same shape quietly falls back to single-device attention
    out = multi_head_attention(q, k, v, backend="auto", use_flash=False)
    ref = _einsum_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    with pytest.raises(ValueError, match="unknown attention_backend"):
        multi_head_attention(q, k, v, backend="ulyses")


def test_trivial_axis_falls_back():
    mesh = MeshConfig(dp=8).build()  # cp == 1
    q, k, v = make_qkv()
    out = ring_attention(q, k, v, mesh=mesh, causal=True)
    ref = _einsum_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_uneven_seq_raises():
    mesh = cp_mesh()
    q, k, v = make_qkv(S=60)
    with pytest.raises(ValueError, match="not divisible"):
        ring_attention(q, k, v, mesh=mesh)


def test_model_uses_cp_from_ambient_mesh():
    """A tiny Llama forward under a cp=8 AcceleratorState mesh matches the
    cp=1 result — the backend swap is transparent."""
    from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig.tiny(use_flash_attention=False)
    model = LlamaForCausalLM(cfg)
    params = model.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=64)
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0, cfg.vocab_size)

    ref_logits = model.apply({"params": params}, ids)

    AcceleratorState._reset_state()
    state = AcceleratorState(mesh_config=MeshConfig(dp=1, cp=8))
    assert state.mesh.shape["cp"] == 8
    cp_logits = model.apply({"params": params}, ids)
    np.testing.assert_allclose(
        np.asarray(cp_logits), np.asarray(ref_logits), atol=2e-4, rtol=2e-4
    )
