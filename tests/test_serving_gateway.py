"""HTTP serving gateway + multi-replica router (serving.router/gateway).

The acceptance-critical properties pinned here:

* END-TO-END EXACTNESS over real HTTP on localhost: completions (JSON
  and SSE-streamed) are token-identical to offline
  ``generation.generate`` for the same (prompt, seed, sampling).
* FAILOVER — killing 1 of 2 replicas mid-stream resumes every in-flight
  request on the survivor with ZERO duplicated and ZERO lost tokens
  (greedy resumption via ``prompt + tokens_emitted`` re-prefill is
  bit-exact); the dead replica is fenced (HEALTHY -> FAILED) and the
  router's counters record the event.
* ROUTING — least-loaded replica selection over free slots, DRAINING
  replicas out of rotation, QueueFull only when EVERY healthy replica is
  saturated.
* HTTP CONTRACT — /healthz, /readyz (503 while draining or with no
  healthy replica), /metrics in Prometheus text format carrying the
  fleet-MERGED engine counters; backpressure mapped to status codes
  (429 + Retry-After on queue-full, 408 on deadline, 413 on body cap,
  400 on malformed requests); graceful drain semantics.
* MULTI-TENANCY — requests carry an ``adapter`` name end to end:
  per-tenant streams are token-identical to offline generation on
  merged weights, the router prefers adapter-resident replicas,
  failover re-routes a tenant onto a survivor that lazily hot-loads
  the adapter row, unknown names map to HTTP 404 and bank pressure to
  a structured 503 that never poisons the engine.

Every server binds port 0 (OS-assigned ephemeral) — no fixed-port
flakes. Timing-sensitive failover tests run on bench's deterministic-
sleep model, like test_serving.py's slow-motion engine.
"""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.adapters import (  # noqa: E402
    AdapterBank,
    LoRAConfig,
    merge_adapter,
)
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.serving import (  # noqa: E402
    FleetRequest,
    GatewayConfig,
    QueueFull,
    ReplicaSet,
    ReplicaState,
    RequestStatus,
    ServingEngine,
    ServingGateway,
    ServingStats,
)
from accelerate_tpu.observability import (  # noqa: E402
    lint_prometheus_text,
    validate_chrome_trace,
)

EOS = 7

PROMPTS = [
    np.array([[3, 5, 7, 11, 2]], np.int32),
    np.array([[1, 4, 9]], np.int32),
    np.array([[8, 6, 4, 2, 10, 12, 14]], np.int32),
    np.array([[42]], np.int32),
]


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


@pytest.fixture(scope="module")
def sleepy(tiny):
    """Deterministic-sleep twin of the tiny model (~15 ms per forward):
    wide enough slot-occupancy windows to kill a replica mid-stream
    race-free on any host."""
    cfg, _, params = tiny
    m = bench._sleepy_llama_cls(step_ms=15.0)(cfg)
    return m, params


def _offline(m, params, prompt, n, seed=None):
    rng = None if seed is None else jax.random.PRNGKey(seed)
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=EOS, rng=rng)
    return np.asarray(out)[0, prompt.shape[1]:]


def _assert_matches_offline(got, ref, n):
    got = np.asarray(got)
    assert np.array_equal(got, ref[: len(got)]), (got, ref)
    if len(got) < n:
        assert got[-1] == EOS and np.all(ref[len(got):] == EOS), (got, ref)


def _fleet(m, params, n=2, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("eos_token_id", EOS)
    return ReplicaSet.from_factory(
        lambda: ServingEngine(m, params, **kw), n)


# -- HTTP helpers ------------------------------------------------------
def _post(url, payload, timeout=60, headers=None):
    req = urllib.request.Request(
        url + "/v1/completions", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(url, path, timeout=10):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as resp:
            return resp.status, resp.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _sse(url, payload, timeout=60, headers=None):
    """(streamed tokens, final summary event)."""
    req = urllib.request.Request(
        url + "/v1/completions",
        data=json.dumps(dict(payload, stream=True)).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    tokens, final = [], None
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        for line in resp:
            line = line.strip()
            if not line.startswith(b"data: "):
                continue
            ev = json.loads(line[6:])
            if ev.get("done"):
                final = ev
                break
            tokens.append(ev["token"])
    return tokens, final


@pytest.fixture(scope="module")
def gateway(tiny):
    """Shared 2-replica gateway on an ephemeral port (warmup paid once).
    Only stateless/read-only tests use it; lifecycle tests build their
    own."""
    _, m, params = tiny
    rs = _fleet(m, params, n=2)
    gw = ServingGateway(rs, config=GatewayConfig(port=0))
    gw.start()
    yield gw
    if gw._server is not None:
        gw.shutdown(drain=False)
    elif rs.replicas[0].engine.running:
        rs.shutdown(drain=False)


class TestReplicaSet:
    @pytest.mark.slow
    def test_submit_matches_offline(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=2)
        try:
            n = 12
            reqs = [rs.submit(p, max_new_tokens=n, seed=0) for p in PROMPTS]
            for p, r in zip(PROMPTS, reqs):
                _assert_matches_offline(r.result(timeout=120),
                                        _offline(m, params, p, n), n)
                assert r.failovers == 0 and len(r.replica_trail) == 1
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_routing_prefers_free_slots(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=2)
        try:
            # Two long requests land on DIFFERENT replicas: after the first
            # occupies a slot on its replica, the other replica has more
            # free slots and must win the next routing decision.
            r1 = rs.submit(PROMPTS[0], max_new_tokens=30, seed=0)
            deadline = time.monotonic() + 30
            while not r1.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            r2 = rs.submit(PROMPTS[1], max_new_tokens=30, seed=0)
            r1.wait(timeout=120), r2.wait(timeout=120)
            assert r1.replica_trail[0] != r2.replica_trail[0]
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_draining_replica_leaves_rotation(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=2)
        try:
            rs.drain_replica(0)
            assert rs.replica_states()[0] is ReplicaState.DRAINING
            assert rs.ready  # replica 1 still serves
            reqs = [rs.submit(p, max_new_tokens=4, seed=0) for p in PROMPTS]
            for r in reqs:
                r.result(timeout=120)
                assert r.replica_trail == [1]
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_queue_full_only_when_all_replicas_saturated(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=1, max_queued=1)
        try:
            # 2 replicas x (1 slot + 1 queued) = 4 accepted, 5th bounces.
            # Let the first pair reach their decode slots before loading
            # the queues — until a request is admitted, the 1-deep queue
            # IS the replica's whole capacity.
            running = [rs.submit(PROMPTS[0], max_new_tokens=30, seed=0)
                       for _ in range(2)]
            deadline = time.monotonic() + 60
            while (min(len(r.tokens) for r in running) < 1
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert min(len(r.tokens) for r in running) >= 1
            reqs = running + [rs.submit(PROMPTS[0], max_new_tokens=30, seed=0)
                              for _ in range(2)]
            with pytest.raises(QueueFull):
                rs.submit(PROMPTS[1], max_new_tokens=2, seed=0)
            for r in reqs:
                r.cancel()
            for r in reqs:
                r.wait(timeout=120)
        finally:
            rs.shutdown(drain=False)

    @pytest.mark.slow
    def test_merged_stats_sum_replicas(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=2)
        try:
            for p in PROMPTS:
                rs.submit(p, max_new_tokens=4, seed=0).result(timeout=120)
            merged = rs.merged_stats()
            assert isinstance(merged, ServingStats)
            s = merged.summary()
            per = [r.engine.serving_metrics() for r in rs.replicas]
            assert s["requests_submitted"] == sum(
                x["requests_submitted"] for x in per) == len(PROMPTS)
            assert s["requests_completed"] == len(PROMPTS)
            assert s["decode_tokens"] == sum(x["decode_tokens"] for x in per)
            fm = rs.fleet_metrics()
            assert fm["replicas"] == 2 and fm["replicas_healthy"] == 2
            assert fm["fleet_submitted"] == len(PROMPTS)
            assert fm["fleet_failovers"] == 0
        finally:
            rs.shutdown()

    def test_mismatched_replicas_rejected(self, tiny):
        _, m, params = tiny
        a = ServingEngine(m, params, max_slots=1, max_len=32,
                          eos_token_id=EOS, autostart=False, warmup=False)
        b = ServingEngine(m, params, max_slots=1, max_len=32,
                          eos_token_id=EOS + 1, autostart=False, warmup=False)
        with pytest.raises(ValueError, match="disagree"):
            ReplicaSet([a, b])
        with pytest.raises(ValueError):
            ReplicaSet([])


class TestFailover:
    @pytest.mark.slow
    def test_kill_one_of_two_resumes_streams_exactly(self, sleepy):
        """The tentpole acceptance test: kill 1 of 2 replicas with streams
        in flight on BOTH; every request finishes on the survivor with
        zero duplicated and zero lost tokens (greedy = bit-exact)."""
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=4, prefill_chunk=16,
                    prefix_cache_mb=4.0)
        n = 24
        refs = [_offline(m, params, p, n) for p in PROMPTS]
        try:
            reqs = [rs.submit(p, max_new_tokens=n, seed=0) for p in PROMPTS]
            deadline = time.monotonic() + 60
            while (min(len(r.tokens) for r in reqs) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert min(len(r.tokens) for r in reqs) >= 3, "streams stalled"
            victim = reqs[0].replica_trail[0]
            rs.kill_replica(victim)
            for r in reqs:
                assert r.wait(timeout=120)
            for r, ref in zip(reqs, refs):
                assert r.status is RequestStatus.COMPLETED
                _assert_matches_offline(r.tokens, ref, n)
            moved = [r for r in reqs if r.replica_trail[0] == victim]
            assert moved, "no request was on the killed replica"
            for r in moved:
                assert r.failovers == 1
                assert r.replica_trail == [victim, 1 - victim]
            states = rs.replica_states()
            assert states[victim] is ReplicaState.FAILED
            assert states[1 - victim] is ReplicaState.HEALTHY
            fm = rs.fleet_metrics()
            assert fm["fleet_fences"] == 1
            assert fm["fleet_failovers"] == len(moved)
            assert fm["replicas_failed"] == 1
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_queued_requests_fail_over_too(self, sleepy):
        """Requests still in the dead replica's ADMISSION QUEUE (never
        admitted, zero tokens) resubmit from scratch on the survivor."""
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=1, max_queued=4)
        n = 10
        try:
            # Saturate both slots, then queue two more (one per replica).
            running = [rs.submit(PROMPTS[0], max_new_tokens=30, seed=0)
                       for _ in range(2)]
            queued = [rs.submit(p, max_new_tokens=n, seed=0)
                      for p in PROMPTS[1:3]]
            victim = running[0].replica_trail[0]
            rs.kill_replica(victim)
            for r in running + queued:
                assert r.wait(timeout=120)
            for r, p in zip(queued, PROMPTS[1:3]):
                assert r.status is RequestStatus.COMPLETED
                _assert_matches_offline(r.tokens,
                                        _offline(m, params, p, n), n)
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_cancel_suppresses_failover(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=2)
        try:
            r = rs.submit(PROMPTS[0], max_new_tokens=40, seed=0)
            deadline = time.monotonic() + 30
            while not r.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            r.cancel()
            rs.kill_replica(r.replica_trail[0])
            assert r.wait(timeout=60)
            # Terminal state must be cancelled (or already-failed), never a
            # resumed stream on the survivor.
            assert r.failovers == 0
            assert r.status in (RequestStatus.CANCELLED, RequestStatus.FAILED)
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_no_survivor_fails_cleanly(self, sleepy):
        m, params = sleepy
        rs = ReplicaSet([ServingEngine(m, params, max_slots=2, max_len=64,
                                       eos_token_id=EOS)])
        try:
            r = rs.submit(PROMPTS[0], max_new_tokens=40, seed=0)
            deadline = time.monotonic() + 30
            while not r.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            rs.kill_replica(0)
            assert r.wait(timeout=60)
            assert r.status is RequestStatus.FAILED
            assert not rs.ready
            with pytest.raises(RuntimeError, match="no healthy replica"):
                rs.submit(PROMPTS[1], max_new_tokens=2)
        finally:
            rs.shutdown()


class TestGatewayHTTP:
    def test_completion_matches_offline(self, gateway, tiny):
        _, m, params = tiny
        n = 12
        for i, p in enumerate(PROMPTS):
            code, out, _ = _post(gateway.url, {
                "prompt": p[0].tolist(), "max_new_tokens": n, "seed": 0})
            assert code == 200 and out["status"] == "completed"
            assert out["prompt_len"] == p.shape[1]
            _assert_matches_offline(out["tokens"],
                                    _offline(m, params, p, n), n)

    def test_sse_stream_matches_offline(self, gateway, tiny):
        _, m, params = tiny
        n = 12
        p = PROMPTS[0]
        tokens, final = _sse(gateway.url, {
            "prompt": p[0].tolist(), "max_new_tokens": n, "seed": 0})
        _assert_matches_offline(tokens, _offline(m, params, p, n), n)
        assert final["done"] and final["status"] == "completed"
        assert final["tokens"] == tokens  # summary == stream, no dup/loss
        assert final["trace_id"]  # done-summary carries the correlation id

    def test_nested_prompt_and_default_max_new(self, gateway):
        code, out, _ = _post(gateway.url,
                             {"prompt": PROMPTS[1].tolist(), "seed": 0})
        assert code == 200
        assert (len(out["tokens"])
                <= gateway.config.default_max_new_tokens)

    def test_healthz_readyz(self, gateway):
        assert _get(gateway.url, "/healthz")[0] == 200
        code, body = _get(gateway.url, "/readyz")
        assert code == 200 and "ready" in body

    def test_metrics_prometheus_text(self, gateway):
        _post(gateway.url, {"prompt": [1, 2, 3], "max_new_tokens": 2,
                            "seed": 0})
        code, text = _get(gateway.url, "/metrics")
        assert code == 200
        lines = text.splitlines()
        # Exposition format: "# TYPE name type" declarations + "name value".
        assert any(l.startswith("# TYPE accelerate_tpu_serving_")
                   for l in lines)
        metrics = {}
        for l in lines:
            if l.startswith("#") or "{" in l:
                continue
            name, val = l.rsplit(" ", 1)
            metrics[name] = float(val)
        assert metrics["accelerate_tpu_serving_replicas"] == 2
        assert metrics["accelerate_tpu_serving_replicas_healthy"] == 2
        assert metrics["accelerate_tpu_serving_requests_completed"] >= 1
        assert metrics["accelerate_tpu_gateway_http_requests"] >= 1
        assert metrics["accelerate_tpu_gateway_http_2xx"] >= 1
        # The labeled per-route counter series is present too.
        assert any(l.startswith(
            'accelerate_tpu_gateway_responses_total{route="/v1/completions"')
            for l in lines)
        # The whole exposition is scrape-clean (HELP/TYPE per family,
        # cumulative buckets ending at +Inf, no duplicate series).
        assert lint_prometheus_text(text) == []
        for hist in ("ttft_ms", "itl_ms", "queue_wait_ms",
                     "prefill_chunk_ms"):
            fam = f"accelerate_tpu_serving_{hist}_hist"
            assert f"# TYPE {fam} histogram" in text
            assert f'{fam}_bucket{{le="+Inf"}}' in text
        assert "accelerate_tpu_xla_compile_events_total" in text

    def test_bad_requests_get_400(self, gateway):
        for payload in ({}, {"prompt": []}, {"prompt": "text"},
                        {"prompt": [1, 2], "max_new_tokens": 0},
                        {"prompt": [1, 2], "max_new_tokens": "four"},
                        {"prompt": [1, 2], "timeout": -1},
                        {"prompt": [1, 2], "seed": "zero"}):
            code, out, _ = _post(gateway.url, payload)
            assert code == 400, payload
            assert "error" in out
        # Over the engine's max_len -> engine-side ValueError -> 400 too.
        code, out, _ = _post(gateway.url,
                             {"prompt": [1] * 60, "max_new_tokens": 30})
        assert code == 400 and "max_len" in out["error"]

    def test_unknown_route_404(self, gateway):
        assert _get(gateway.url, "/v2/nope")[0] == 404
        req = urllib.request.Request(
            gateway.url + "/v1/nope", data=b"{}",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 404

    @pytest.mark.slow
    def test_body_cap_413(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=1)
        gw = ServingGateway(rs, config=GatewayConfig(
            port=0, max_body_bytes=64))
        gw.start()
        try:
            code, out, _ = _post(gw.url, {"prompt": [1] * 500})
            assert code == 413 and "max_body_bytes" in out["error"]
            assert out["trace_id"]
        finally:
            gw.shutdown()

    def test_invalid_json_400(self, gateway):
        req = urllib.request.Request(
            gateway.url + "/v1/completions", data=b"{not json",
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400


class TestGatewayBackpressure:
    @pytest.mark.slow
    def test_queue_full_429_with_retry_after(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=1, max_slots=1, max_queued=1)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        try:
            first = rs.submit(PROMPTS[0], max_new_tokens=40, seed=0)
            deadline = time.monotonic() + 60
            while not first.tokens and time.monotonic() < deadline:
                time.sleep(0.005)  # in its slot -> the queue is free again
            blockers = [first,
                        rs.submit(PROMPTS[0], max_new_tokens=40, seed=0)]
            code, out, headers = _post(gw.url, {"prompt": [1, 2, 3],
                                                "max_new_tokens": 2})
            assert code == 429
            assert "Retry-After" in headers
            assert out["trace_id"] == headers["X-Request-Id"]
            for b in blockers:
                b.cancel()
            for b in blockers:
                b.wait(timeout=120)
        finally:
            gw.shutdown(drain=False)

    @pytest.mark.slow
    def test_deadline_408(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=1, max_slots=1, max_queued=4)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        try:
            blocker = rs.submit(PROMPTS[0], max_new_tokens=50, seed=0)
            deadline = time.monotonic() + 60
            while not blocker.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            # Queued behind a ~1 s stream with a 100 ms deadline.
            code, out, _ = _post(gw.url, {"prompt": [1, 2, 3],
                                          "max_new_tokens": 2,
                                          "timeout": 0.1})
            assert code == 408 and out["status"] == "timed_out"
            assert out["trace_id"]
            blocker.cancel()
            blocker.wait(timeout=120)
        finally:
            gw.shutdown(drain=False)

    @pytest.mark.slow
    def test_connection_cap_503(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=1)
        gw = ServingGateway(rs, config=GatewayConfig(port=0,
                                                     max_connections=1))
        gw.start()
        try:
            gw._conn_slots.acquire()  # simulate a busy in-flight exchange
            code, body = _get(gw.url, "/readyz")
            assert code == 503
            gw._conn_slots.release()
            assert _get(gw.url, "/readyz")[0] == 200
        finally:
            gw.shutdown()


class TestGatewayTracing:
    def test_trace_id_minted_and_echoed(self, gateway):
        # No header -> the gateway mints one and echoes it body + header.
        code, out, headers = _post(gateway.url, {
            "prompt": [1, 2, 3], "max_new_tokens": 2, "seed": 0})
        assert code == 200 and out["trace_id"]
        assert headers["X-Request-Id"] == out["trace_id"]
        # Well-formed client id -> carried through verbatim.
        code, out, headers = _post(
            gateway.url, {"prompt": [1, 2, 3], "max_new_tokens": 2,
                          "seed": 0},
            headers={"X-Request-Id": "client-id_1.2:3"})
        assert out["trace_id"] == "client-id_1.2:3"
        assert headers["X-Request-Id"] == "client-id_1.2:3"
        # Garbage client id -> sanitized away, fresh id minted.
        code, out, _ = _post(
            gateway.url, {"prompt": [1, 2, 3], "max_new_tokens": 2,
                          "seed": 0},
            headers={"X-Request-Id": "bad id\twith junk"})
        assert out["trace_id"] and out["trace_id"] != "bad id\twith junk"

    def test_error_bodies_carry_trace_id(self, gateway):
        # 400 (malformed) and 404-adapter-style errors happen before a
        # FleetRequest exists; the minted id must still be in the body.
        code, out, headers = _post(gateway.url, {"prompt": "text"},
                                   headers={"X-Request-Id": "err-path-1"})
        assert code == 400 and out["trace_id"] == "err-path-1"
        assert headers["X-Request-Id"] == "err-path-1"

    def test_debug_trace_endpoint(self, gateway):
        tid = "debug-trace-probe-1"
        code, out, _ = _post(gateway.url,
                             {"prompt": [2, 4, 6], "max_new_tokens": 3,
                              "seed": 0},
                             headers={"X-Request-Id": tid})
        assert code == 200 and out["trace_id"] == tid
        code, body = _get(gateway.url, f"/debug/trace?id={tid}")
        assert code == 200
        trace = json.loads(body)
        assert validate_chrome_trace(trace) == []
        evs = [e for e in trace["traceEvents"] if e.get("ph") != "M"]
        names = {e["name"] for e in evs}
        assert {"submit", "queue_wait", "first_token",
                "prefill_chunk", "itl", "retire"} <= names
        assert all(e["args"]["trace_id"] == tid for e in evs
                   if "args" in e and "trace_id" in e.get("args", {}))
        # Unfiltered dump is the whole fleet timeline, still valid.
        code, body = _get(gateway.url, "/debug/trace")
        assert code == 200
        assert validate_chrome_trace(json.loads(body)) == []
        # Unknown id -> 404, malformed id -> 400.
        assert _get(gateway.url, "/debug/trace?id=nosuchtrace0000")[0] == 404
        assert _get(gateway.url, "/debug/trace?id=bad%20id%09junk")[0] == 400

    @pytest.mark.slow
    def test_failover_trace_spans_both_replicas(self, sleepy):
        """The e2e observability acceptance test: an SSE stream survives a
        replica kill; the final done-summary carries the client's trace
        id, /debug/trace?id= returns ONE valid Chrome trace whose spans
        cover the dead replica's prefill/decode AND the survivor's
        resumed continuation, and the failover report carries the dead
        replica's flight-recorder postmortem with the fatal event."""
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=4, prefill_chunk=16,
                    prefix_cache_mb=4.0)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        tid = "failover-e2e-trace"
        n = 24
        ref = _offline(m, params, PROMPTS[0], n)
        try:
            # Keep both replicas occupied so the kill has streams on each.
            ballast = [rs.submit(p, max_new_tokens=n, seed=0)
                       for p in PROMPTS[1:3]]
            got = {}

            def client():
                got["tokens"], got["final"] = _sse(
                    gw.url, {"prompt": PROMPTS[0][0].tolist(),
                             "max_new_tokens": n, "seed": 0},
                    timeout=120, headers={"X-Request-Id": tid})

            t = threading.Thread(target=client, daemon=True)
            t.start()
            # Wait until the traced stream is decoding, then kill its host.
            deadline = time.monotonic() + 60
            victim = None
            while time.monotonic() < deadline:
                evs = rs.chrome_trace(tid)["traceEvents"]
                itl = [e for e in evs if e["name"] == "itl"]
                if len(itl) >= 3:
                    victim = next(i for i, r in enumerate(rs.replicas)
                                  if r.engine.tracer.pid == itl[0]["pid"])
                    break
                time.sleep(0.005)
            assert victim is not None, "traced stream never started decoding"
            rs.kill_replica(victim)
            t.join(timeout=120)
            assert not t.is_alive(), "SSE client did not finish"
            # Stream resumed exactly; the done-summary carries OUR id.
            _assert_matches_offline(got["tokens"], ref, n)
            final = got["final"]
            assert final["trace_id"] == tid
            assert final["failovers"] == 1
            assert final["replica_trail"] == [victim, 1 - victim]
            for b in ballast:
                b.wait(timeout=120)
            # One valid Chrome trace spanning both replicas' pid lanes.
            code, body = _get(gw.url, f"/debug/trace?id={tid}")
            assert code == 200
            trace = json.loads(body)
            assert validate_chrome_trace(trace) == []
            span_pids = {e["pid"] for e in trace["traceEvents"]
                         if e.get("ph") != "M"}
            pid_a = rs.engine(victim).tracer.pid
            pid_b = rs.engine(1 - victim).tracer.pid
            assert {pid_a, pid_b} <= span_pids
            by_pid = {}
            for e in trace["traceEvents"]:
                if e.get("ph") != "M":
                    by_pid.setdefault(e["pid"], set()).add(e["name"])
            # Replica A saw the original queue->prefill->decode spans ...
            assert {"queue_wait", "prefill_chunk", "itl"} <= by_pid[pid_a]
            # ... and the survivor re-admitted + decoded the continuation.
            assert {"queue_wait", "prefill_chunk", "itl"} <= by_pid[pid_b]
            # The failover report attaches the dead replica's postmortem.
            reports = [r for r in rs.failover_reports
                       if r["trace_id"] == tid]
            assert len(reports) == 1
            rep = reports[0]
            assert rep["replica"] == victim
            pm = rep["flight_recorder"]
            assert pm is not None and pm["events"]
            kinds = [e["kind"] for e in pm["events"]]
            assert "fatal" in kinds and "kill" in kinds
        finally:
            gw.shutdown(drain=False)


class TestDrainSemantics:
    @pytest.mark.slow
    def test_drain_stops_admission_finishes_inflight(self, sleepy):
        m, params = sleepy
        rs = _fleet(m, params, n=2, max_slots=2)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        try:
            n = 20
            inflight = rs.submit(PROMPTS[0], max_new_tokens=n, seed=0)
            deadline = time.monotonic() + 30
            while not inflight.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            gw.drain()
            # readyz flips 503, new completions are refused...
            code, body = _get(gw.url, "/readyz")
            assert code == 503 and "draining" in body
            code, out, headers = _post(gw.url, {"prompt": [1, 2],
                                                "max_new_tokens": 2})
            assert code == 503 and "Retry-After" in headers
            assert out["trace_id"]  # every error body carries the id
            # ...but liveness holds and the in-flight stream completes.
            assert _get(gw.url, "/healthz")[0] == 200
            assert inflight.wait(timeout=120)
            assert inflight.status is RequestStatus.COMPLETED
            _assert_matches_offline(inflight.tokens,
                                    _offline(m, params, PROMPTS[0], n), n)
        finally:
            gw.shutdown()

    @pytest.mark.slow
    def test_shutdown_is_idempotent_and_final(self, tiny):
        _, m, params = tiny
        rs = _fleet(m, params, n=1)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        url = gw.url
        gw.shutdown()
        gw.shutdown()  # second call must be a no-op, not an error
        with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
            urllib.request.urlopen(url + "/healthz", timeout=2)
        with pytest.raises(RuntimeError):
            rs.engine(0).submit(PROMPTS[0], max_new_tokens=2)

    @pytest.mark.slow
    def test_engine_autowrap_and_context_manager(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS)
        with ServingGateway(eng, config=GatewayConfig(port=0)) as gw:
            assert isinstance(gw.replica_set, ReplicaSet)
            code, out, _ = _post(gw.url, {"prompt": [1, 2, 3],
                                          "max_new_tokens": 2, "seed": 0})
            assert code == 200
        assert not eng.running


@pytest.mark.slow
class TestFailoverSoak:
    def test_waves_of_streams_survive_sequential_kills(self, sleepy):
        """Nightly soak: 3 replicas, continuous request waves, kill two
        replicas one after another mid-traffic — every request must end
        terminal (completed exactly, or failed ONLY with the no-survivor
        error after the last kill), and the final survivor must still
        serve fresh traffic exactly."""
        m, params = sleepy
        rs = _fleet(m, params, n=3, max_slots=4, max_queued=16,
                    prefill_chunk=16, prefix_cache_mb=4.0)
        n = 16
        refs = {i: _offline(m, params, p, n) for i, p in enumerate(PROMPTS)}
        done: list[FleetRequest] = []
        try:
            for wave in range(3):
                reqs = [(i, rs.submit(p, max_new_tokens=n, seed=0))
                        for i, p in enumerate(PROMPTS)]
                time.sleep(0.15)
                if wave < 2:
                    victims = [r.index for r in rs.replicas
                               if r.state is ReplicaState.HEALTHY]
                    rs.kill_replica(victims[0])
                for i, r in reqs:
                    assert r.wait(timeout=180)
                    assert r.status is RequestStatus.COMPLETED, (wave, i, r)
                    _assert_matches_offline(r.tokens, refs[i], n)
                    done.append(r)
            fm = rs.fleet_metrics()
            assert fm["replicas_failed"] == 2
            assert fm["replicas_healthy"] == 1
            assert fm["fleet_fences"] == 2
            total_failovers = sum(r.failovers for r in done)
            assert total_failovers == fm["fleet_failovers"] > 0
        finally:
            rs.shutdown()


# -- multi-tenant LoRA adapters over the fleet -------------------------
def _adapter_fleet(m, params, adapters, n=2, rank=4, **kw):
    """Bank-equipped fleet with every adapter registered fleet-wide.

    Residency is lazy (a bank row loads at first acquire, on the engine
    thread), so a freshly built fleet has nothing resident — exactly the
    starting state the survivor-must-load failover test needs."""
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("eos_token_id", EOS)
    bank_rows = kw.pop("max_adapters", len(adapters) + 1)
    rs = ReplicaSet.from_factory(
        lambda: ServingEngine(
            m, params,
            adapters=AdapterBank(params, config=LoRAConfig(rank=rank),
                                 max_adapters=bank_rows), **kw), n)
    for name, ad in adapters.items():
        rs.register_adapter(name, ad)
    return rs


class TestAdapterGatewayHTTP:
    """HTTP surface of multi-tenant serving: per-tenant exactness, the
    404/400 contract for bad adapter names, and labeled /metrics."""

    @pytest.fixture(scope="class")
    def agw(self, tiny):
        _, m, params = tiny
        ads = dict(zip(("acme", "globex"),
                       bench._test_lora_adapters(params, 2, rank=4)))
        rs = _adapter_fleet(m, params, ads, n=1)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        yield gw, m, params, ads
        gw.shutdown(drain=False)

    def test_tenants_exact_and_isolated(self, agw):
        gw, m, params, ads = agw
        n, p = 12, PROMPTS[0]
        streams = {}
        for name in (None, "acme", "globex"):
            payload = {"prompt": p[0].tolist(), "max_new_tokens": n,
                       "seed": 0}
            if name:
                payload["adapter"] = name
            code, out, _ = _post(gw.url, payload)
            assert code == 200 and out["status"] == "completed", out
            ref_params = merge_adapter(params, ads[name]) if name else params
            _assert_matches_offline(out["tokens"],
                                    _offline(m, ref_params, p, n), n)
            streams[name] = tuple(out["tokens"])
        # Same prompt, three tenants (base + two adapters), three streams.
        assert len(set(streams.values())) == 3, streams

    def test_unknown_adapter_404(self, agw):
        gw, *_ = agw
        code, out, _ = _post(gw.url, {"prompt": [1, 2, 3],
                                      "max_new_tokens": 4,
                                      "adapter": "nobody"})
        assert code == 404 and out["error"] == "unknown_adapter"
        assert "nobody" in out["detail"]

    def test_malformed_adapter_400(self, agw):
        gw, *_ = agw
        for bad in ("", 7, ["acme"]):
            code, out, _ = _post(gw.url, {"prompt": [1, 2], "adapter": bad})
            assert code == 400 and "adapter" in out["error"], bad

    def test_metrics_carry_adapter_labels(self, agw):
        gw, *_ = agw
        _post(gw.url, {"prompt": [1, 2, 3], "max_new_tokens": 2,
                       "seed": 0, "adapter": "acme"})
        code, text = _get(gw.url, "/metrics")
        assert code == 200
        assert any(l.startswith(
            'accelerate_tpu_serving_adapter_requests{adapter="acme"}')
            for l in text.splitlines())
        # The flat "adapter/<name>/..." internal keys never leak as raw
        # (invalid) Prometheus metric names.
        assert "adapter/" not in text


class TestAdapterFailover:
    @pytest.mark.slow
    def test_router_prefers_resident_replica(self, tiny):
        """Once a tenant's row is resident somewhere, subsequent requests
        for that tenant stick to it instead of ping-ponging rows across
        banks (load still wins between equally-resident replicas)."""
        _, m, params = tiny
        (ad,) = bench._test_lora_adapters(params, 1, rank=4)
        rs = _adapter_fleet(m, params, {"acme": ad}, n=2)
        try:
            first = rs.submit(PROMPTS[0], max_new_tokens=4, seed=0,
                              adapter="acme")
            assert first.wait(timeout=120)
            home = first.replica_trail[0]
            assert rs.replicas[home].engine.adapter_resident("acme")
            for _ in range(3):
                r = rs.submit(PROMPTS[1], max_new_tokens=4, seed=0,
                              adapter="acme")
                assert r.wait(timeout=120)
                assert r.replica_trail == [home]
            other = rs.replicas[1 - home].engine
            assert not other.adapter_resident("acme")
            assert other.adapters.counters()["loads"] == 0
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_failover_preserves_tenant_and_loads_on_survivor(self, sleepy):
        """Kill the replica serving a tenant's stream mid-flight. The
        retry must carry the adapter with it: the survivor — which has
        never served this tenant, so its bank row is NOT resident —
        lazily hot-loads the adapter and resumes the stream token-exact
        against the merged-weights offline reference."""
        m, params = sleepy
        (ad,) = bench._test_lora_adapters(params, 1, rank=4)
        rs = _adapter_fleet(m, params, {"acme": ad}, n=2, max_slots=2)
        n = 24
        ref_t = _offline(m, merge_adapter(params, ad), PROMPTS[0], n)
        ref_b = _offline(m, params, PROMPTS[1], n)
        try:
            rt = rs.submit(PROMPTS[0], max_new_tokens=n, seed=0,
                           adapter="acme")
            rb = rs.submit(PROMPTS[1], max_new_tokens=n, seed=0)
            deadline = time.monotonic() + 60
            while (min(len(rt.tokens), len(rb.tokens)) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert min(len(rt.tokens), len(rb.tokens)) >= 3
            victim = rt.replica_trail[0]
            survivor = 1 - victim
            assert rs.replicas[victim].engine.adapter_resident("acme")
            assert not rs.replicas[survivor].engine.adapter_resident("acme")
            rs.kill_replica(victim)
            assert rt.wait(timeout=120) and rb.wait(timeout=120)
            assert rt.status is RequestStatus.COMPLETED, rt
            assert rb.status is RequestStatus.COMPLETED, rb
            _assert_matches_offline(rt.tokens, ref_t, n)
            _assert_matches_offline(rb.tokens, ref_b, n)
            assert rt.adapter == "acme"
            assert rt.failovers == 1
            assert rt.replica_trail == [victim, survivor]
            # Finishing the stream forced the survivor to hot-load the row.
            surv = rs.replicas[survivor].engine
            assert surv.adapter_resident("acme")
            assert surv.adapters.counters()["loads"] == 1
        finally:
            rs.shutdown()

    @pytest.mark.slow
    def test_bank_full_maps_to_structured_503(self, sleepy):
        """Every non-base row pinned by an in-flight tenant: a second
        tenant's HTTP request gets a structured 503 (adapter_bank_full +
        Retry-After) while the replica stays HEALTHY, and the same
        request succeeds once the pin releases."""
        m, params = sleepy
        ads = dict(zip(("acme", "globex"),
                       bench._test_lora_adapters(params, 2, rank=4)))
        rs = _adapter_fleet(m, params, ads, n=1, max_adapters=2,
                            max_slots=2)
        gw = ServingGateway(rs, config=GatewayConfig(port=0))
        gw.start()
        try:
            long = rs.submit(PROMPTS[0], max_new_tokens=48, seed=0,
                             ignore_eos=True, adapter="acme")
            deadline = time.monotonic() + 60
            while not long.tokens and time.monotonic() < deadline:
                time.sleep(0.01)
            assert long.tokens  # the single non-base row is now pinned
            code, out, hdrs = _post(gw.url, {
                "prompt": PROMPTS[1][0].tolist(), "max_new_tokens": 4,
                "seed": 0, "adapter": "globex"})
            assert code == 503 and out["error"] == "adapter_bank_full"
            assert "globex" in out["detail"]
            assert "Retry-After" in hdrs
            assert rs.replicas[0].state is ReplicaState.HEALTHY
            assert long.wait(timeout=120)
            assert long.status is RequestStatus.COMPLETED
            code, out, _ = _post(gw.url, {
                "prompt": PROMPTS[1][0].tolist(), "max_new_tokens": 4,
                "seed": 0, "adapter": "globex"})
            assert code == 200 and out["status"] == "completed"
        finally:
            gw.shutdown(drain=False)
