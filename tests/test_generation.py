"""KV-cached decoding: exactness vs full-forward greedy, cache threading,
streamed-executor decode (reference capability: transformers' cached
``model.generate`` under the big-model hooks; latency table at
benchmarks/big_model_inference/README.md:26-45)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.big_modeling import dispatch_model
from accelerate_tpu.generation import greedy_generate, supports_kv_cache
from accelerate_tpu.models.llama import (
    LlamaConfig,
    LlamaForCausalLM,
    init_kv_cache,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


PROMPT = np.array([[3, 5, 7, 11, 2], [1, 4, 9, 16, 25]], np.int32)


def naive_greedy(m, params, ids, n):
    ids = jnp.asarray(ids)
    for _ in range(n):
        logits = m.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], -1)[:, None].astype(ids.dtype)
        ids = jnp.concatenate([ids, nxt], axis=1)
    return np.asarray(ids)


class TestCacheThreading:
    def test_prefill_logits_match_full_forward(self, tiny):
        cfg, m, params = tiny
        cache = init_kv_cache(cfg, 2, PROMPT.shape[1], jnp.float32)
        cached_logits, new_cache = m.apply(
            {"params": params}, PROMPT, cache=cache, cache_pos=0
        )
        full_logits = m.apply({"params": params}, PROMPT)
        np.testing.assert_allclose(
            np.asarray(cached_logits), np.asarray(full_logits), rtol=1e-5, atol=1e-5
        )
        assert len(new_cache) == cfg.num_hidden_layers

    def test_incremental_decode_matches_full_forward(self, tiny):
        # Feed tokens one at a time through the cache; logits at each step
        # must match the corresponding column of the full forward.
        cfg, m, params = tiny
        ids = PROMPT[:, :4]
        full = np.asarray(m.apply({"params": params}, ids))
        cache = init_kv_cache(cfg, 2, 4, jnp.float32)
        for t in range(4):
            step_logits, cache = m.apply(
                {"params": params}, ids[:, t : t + 1], cache=cache, cache_pos=t
            )
            np.testing.assert_allclose(
                np.asarray(step_logits)[:, 0], full[:, t], rtol=1e-4, atol=1e-4
            )

    def test_cache_stores_unrepeated_kv_heads(self, tiny):
        cfg, m, params = tiny
        cache = init_kv_cache(cfg, 2, 8)
        assert cache[0]["k"].shape == (2, 8, cfg.num_key_value_heads, cfg.head_dim)


class TestGreedyGenerate:
    def test_matches_naive_full_forward(self, tiny):
        cfg, m, params = tiny
        ref = naive_greedy(m, params, PROMPT, 6)
        out = greedy_generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_eos_freezes_sequence(self, tiny):
        cfg, m, params = tiny
        ref = naive_greedy(m, params, PROMPT, 6)
        eos = int(ref[0, PROMPT.shape[1] + 1])  # force an early stop on row 0
        out = np.asarray(
            greedy_generate(
                m, params, PROMPT, max_new_tokens=6, eos_token_id=eos,
                cache_dtype=jnp.float32,
            )
        )
        stop = PROMPT.shape[1] + 2
        assert (out[0, stop:] == eos).all()

    def test_supports_probe_and_type_error(self, tiny):
        cfg, m, params = tiny
        assert supports_kv_cache(m)
        with pytest.raises(TypeError):
            greedy_generate(object(), params, PROMPT)


class TestStreamedGenerate:
    def test_cached_matches_full_forward_loop(self, tiny):
        cfg, m, params = tiny
        streamed = dispatch_model(m, params=params, device_map={"": "cpu"})
        full = np.asarray(streamed.generate(jnp.asarray(PROMPT), 6, use_cache=False))
        kv = np.asarray(streamed.generate(jnp.asarray(PROMPT), 6))
        np.testing.assert_array_equal(kv, full)

    def test_cached_matches_fused_generate(self, tiny):
        cfg, m, params = tiny
        streamed = dispatch_model(m, params=params, device_map={"": 0})
        kv = np.asarray(streamed.generate(jnp.asarray(PROMPT), 5))
        fused = np.asarray(
            greedy_generate(m, params, PROMPT, max_new_tokens=5, cache_dtype=jnp.bfloat16)
        )
        np.testing.assert_array_equal(kv, fused)

    def test_one_decode_executable_per_kind(self, tiny):
        cfg, m, params = tiny
        streamed = dispatch_model(m, params=params, device_map={"": 0})
        streamed.generate(jnp.asarray(PROMPT), 5)
        cached_keys = [k for k in streamed._jitted if k.endswith("/cached")]
        assert sorted(cached_keys) == ["embed/cached", "head/cached", "layer/cached"]


class TestGPT2Generate:
    @pytest.fixture(scope="class")
    def gpt2(self):
        from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

        cfg = GPT2Config.tiny(use_flash_attention=False)
        m = GPT2LMHeadModel(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
        return cfg, m, params

    @pytest.mark.nightly  # llama's TestGreedyGenerate covers default runs
    def test_fused_matches_naive(self, gpt2):
        cfg, m, params = gpt2
        ref = naive_greedy(m, params, PROMPT, 6)
        out = greedy_generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_streamed_cached_matches(self, gpt2):
        cfg, m, params = gpt2
        streamed = dispatch_model(m, params=params, device_map={"": "cpu"})
        full = np.asarray(streamed.generate(jnp.asarray(PROMPT), 5, use_cache=False))
        kv = np.asarray(streamed.generate(jnp.asarray(PROMPT), 5))
        np.testing.assert_array_equal(kv, full)

    def test_learned_positions_cap_the_prompt_bucket(self):
        """Bucketed-prefill padding must cap at the learned-position table:
        a wpe model with n_positions=32 would otherwise see pad positions
        past its table, whose OOB lookups go non-finite and NaN-poison the
        whole forward (caught live on OPT). Exactness across lengths +
        repetition penalty pins both the cap and the edge-pad seen-set."""
        from accelerate_tpu.models.gpt2 import GPT2Config, GPT2LMHeadModel

        cfg = GPT2Config.tiny(use_flash_attention=False,
                              max_position_embeddings=32)
        m = GPT2LMHeadModel(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        for S in (3, 7, 12):
            ids = (np.arange(S, dtype=np.int32)[None] * 11 + 4) % cfg.vocab_size
            ref = naive_greedy(m, params, ids, 6)
            out = greedy_generate(m, params, ids, max_new_tokens=6,
                                  cache_dtype=jnp.float32)
            np.testing.assert_array_equal(np.asarray(out), ref)
            assert np.isfinite(np.asarray(
                m.apply({"params": params}, jnp.asarray(ids)))).all()
            from accelerate_tpu.generation import generate

            rep = generate(m, params, ids, max_new_tokens=6,
                           cache_dtype=jnp.float32, repetition_penalty=1.3)
            assert np.asarray(rep).shape == (1, S + 6)
            assert (np.asarray(rep) < cfg.vocab_size).all()


class TestSampling:
    def test_temperature_zero_ish_matches_greedy(self, tiny):
        from accelerate_tpu.generation import generate

        cfg, m, params = tiny
        greedy = greedy_generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32)
        cold = generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32,
                        do_sample=True, temperature=1e-4, rng=jax.random.PRNGKey(7))
        np.testing.assert_array_equal(np.asarray(cold), np.asarray(greedy))

    def test_sampling_is_seeded_and_varies(self, tiny):
        from accelerate_tpu.generation import generate

        cfg, m, params = tiny
        a = generate(m, params, PROMPT, max_new_tokens=8, do_sample=True,
                     temperature=1.5, rng=jax.random.PRNGKey(0), cache_dtype=jnp.float32)
        b = generate(m, params, PROMPT, max_new_tokens=8, do_sample=True,
                     temperature=1.5, rng=jax.random.PRNGKey(0), cache_dtype=jnp.float32)
        c = generate(m, params, PROMPT, max_new_tokens=8, do_sample=True,
                     temperature=1.5, rng=jax.random.PRNGKey(1), cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not (np.asarray(a) == np.asarray(c)).all()

    def test_top_k_restricts_support(self, tiny):
        from accelerate_tpu.generation import generate

        cfg, m, params = tiny
        # top_k=1 is greedy regardless of temperature.
        greedy = greedy_generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32)
        k1 = generate(m, params, PROMPT, max_new_tokens=6, do_sample=True, temperature=5.0,
                      top_k=1, rng=jax.random.PRNGKey(3), cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(k1), np.asarray(greedy))

    def test_top_p_tiny_is_greedy(self, tiny):
        from accelerate_tpu.generation import generate

        cfg, m, params = tiny
        greedy = greedy_generate(m, params, PROMPT, max_new_tokens=6, cache_dtype=jnp.float32)
        p0 = generate(m, params, PROMPT, max_new_tokens=6, do_sample=True, temperature=5.0,
                      top_p=1e-9, rng=jax.random.PRNGKey(3), cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(p0), np.asarray(greedy))


class TestMixtralGenerate:
    # NOTE: cached decode runs the experts with no capacity dropping (the
    # faithful inference setting); the uncached reference forward drops past
    # capacity, so exact equality holds only while the router stays under
    # capacity — true for the random-init tiny config used here.
    @pytest.mark.nightly  # llama's TestGreedyGenerate covers default runs
    def test_fused_matches_naive(self):
        from accelerate_tpu.models.mixtral import MixtralConfig, MixtralForCausalLM
        from accelerate_tpu.generation import generate

        cfg = MixtralConfig.tiny_moe(use_flash_attention=False)
        m = MixtralForCausalLM(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
        ids = jnp.asarray(PROMPT)
        ref = ids
        for _ in range(6):
            logits, _ = m.apply({"params": params}, ref)
            ref = jnp.concatenate(
                [ref, jnp.argmax(logits[:, -1], -1)[:, None].astype(ref.dtype)], 1)
        out = generate(m, params, ids, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))


class TestRingKVCache:
    """Sliding-window layers decode from an O(window) ring buffer, not an
    O(max_len) cache (models/llama.py init_kv_cache)."""

    def test_window_layer_cache_is_bounded(self):
        from accelerate_tpu.models.llama import LlamaConfig, init_kv_cache

        cfg = LlamaConfig.tiny(layer_windows=(8, None))
        cache = init_kv_cache(cfg, batch_size=2, max_len=64)
        assert cache[0]["k"].shape[1] == 8 and "pos" in cache[0]
        assert cache[0]["pos"].shape == (2, 8)
        assert cache[1]["k"].shape[1] == 64 and "pos" not in cache[1]

    def test_ring_decode_matches_eager_windowed_forward(self):
        """Greedy decode through the ring cache must equal token-by-token
        eager forwards over the growing sequence (no cache at all) — decode
        goes well past the window so slots genuinely wrap."""
        from accelerate_tpu.generation import generate
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False, sliding_window=8)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        ids = np.arange(5, dtype=np.int32)[None] % cfg.vocab_size

        out = np.asarray(generate(model, params, jnp.asarray(ids), max_new_tokens=16,
                                  cache_dtype=jnp.float32))

        # Greedy self-consistency: attention is causal, so ONE eager forward
        # over the finished sequence reproduces every step's logits — each
        # emitted token must be the argmax at its predecessor position
        # (equivalent to 16 token-by-token forwards, minus 15 re-dispatches
        # at growing lengths).
        logits = np.asarray(
            model.apply({"params": params}, jnp.asarray(out)), np.float32)
        S = ids.shape[1]
        np.testing.assert_array_equal(out[0, S:], logits[0, S - 1:-1].argmax(-1))

    def test_ring_beam_search_matches_full_window(self):
        """Beam search reorders cache leaves on the batch axis — the ring's
        [B, W] pos buffer must ride along; compare vs a window wide enough
        that the full cache path is used with identical semantics."""
        import dataclasses

        from accelerate_tpu.generation import beam_search_generate
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False, sliding_window=24)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(1), batch_size=1, seq_len=8)
        ids = np.arange(4, dtype=np.int32)[None] % cfg.vocab_size
        # window 24 >= every attended length here, so both paths see
        # identical attention; only the cache layout differs (24 < max_len
        # forces the ring, max_len-wide window forces the full cache).
        ring = beam_search_generate(model, params, jnp.asarray(ids), num_beams=3,
                                    max_new_tokens=6, cache_dtype=jnp.float32)
        wide_cfg = dataclasses.replace(cfg, sliding_window=None)
        full = beam_search_generate(LlamaForCausalLM(wide_cfg), params, jnp.asarray(ids),
                                    num_beams=3, max_new_tokens=6, cache_dtype=jnp.float32)
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(full))

    def test_ring_chunked_prefill_matches_eager(self):
        """Multi-token writes at cache_pos > 0 (chunked prefill /
        speculative verification) must see the in-window keys already in
        the ring, matching a full eager windowed forward."""
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM, init_kv_cache

        cfg = LlamaConfig.tiny(use_flash_attention=False, sliding_window=8)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(2), batch_size=1, seq_len=8)
        ids = (np.arange(14, dtype=np.int32)[None] * 3) % cfg.vocab_size

        cache = init_kv_cache(cfg, batch_size=1, max_len=20, dtype=jnp.float32)
        assert "pos" in cache[0]  # window 8 < max_len: rings engaged
        logits1, cache = model.apply({"params": params}, jnp.asarray(ids[:, :6]),
                                     cache=cache, cache_pos=0)
        logits2, cache = model.apply({"params": params}, jnp.asarray(ids[:, 6:14]),
                                     cache=cache, cache_pos=6)

        ref = model.apply({"params": params}, jnp.asarray(ids))
        np.testing.assert_allclose(
            np.asarray(logits2, np.float32), np.asarray(ref[:, 6:14], np.float32),
            atol=2e-4, rtol=2e-3)


class TestPromptLookupGenerate:
    """Speculative (prompt-lookup) decoding must produce EXACTLY the plain
    greedy output — acceptance is decided by the model's own predictions."""

    def _model(self, **cfg_overrides):
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False, **cfg_overrides)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(3), batch_size=1, seq_len=8)
        return model, params, cfg

    @pytest.mark.parametrize("prompt_kind", ["repetitive", "random"])
    def test_matches_plain_greedy(self, prompt_kind):
        from accelerate_tpu.generation import generate, prompt_lookup_generate

        model, params, cfg = self._model()
        if prompt_kind == "repetitive":
            ids = np.tile(np.array([[7, 11, 13]], np.int32), (1, 4))   # abcabcabc...
        else:
            ids = (np.arange(12, dtype=np.int32)[None] * 37 + 5) % cfg.vocab_size
        ref = np.asarray(generate(model, params, jnp.asarray(ids), max_new_tokens=24,
                                  cache_dtype=jnp.float32))
        got = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids),
                                                max_new_tokens=24,
                                                cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_prompt_bucket_shares_one_prefill_compile(self):
        """Nearby prompt lengths must reuse ONE compiled (prefill, loop)
        pair: prefill runs on the 128-bucketed padded prompt with the true
        length traced, so interactive use doesn't recompile per exact
        length — while outputs stay exactly plain greedy for every length."""
        from accelerate_tpu.generation import (_compiled_lookup_generate,
                                               generate, prompt_lookup_generate)

        model, params, cfg = self._model()
        outs = {}
        for S in (5, 9, 12):
            ids = (np.arange(S, dtype=np.int32)[None] * 29 + 3) % cfg.vocab_size
            ref = np.asarray(generate(model, params, jnp.asarray(ids),
                                      max_new_tokens=10, cache_dtype=jnp.float32))
            got = np.asarray(prompt_lookup_generate(
                model, params, jnp.asarray(ids), max_new_tokens=10,
                cache_dtype=jnp.float32))
            np.testing.assert_array_equal(got, ref)
            outs[S] = got
        # All three lengths share a bucket (L and P identical), so the
        # cached prefill must hold exactly ONE jit trace.
        prefill, _ = _compiled_lookup_generate(
            model, 10, None, jnp.float32, 2, 5, 128)
        assert prefill._cache_size() == 1, prefill._cache_size()

    def test_matches_with_eos(self):
        from accelerate_tpu.generation import generate, prompt_lookup_generate

        model, params, cfg = self._model()
        ids = (np.arange(10, dtype=np.int32)[None] * 3) % cfg.vocab_size
        # pick the token greedy actually emits somewhere as the EOS, so the
        # ragged-stop path runs; token 0 fallback if none repeats
        ref_free = np.asarray(generate(model, params, jnp.asarray(ids),
                                       max_new_tokens=16, cache_dtype=jnp.float32))
        eos = int(ref_free[0, 14])
        ref = np.asarray(generate(model, params, jnp.asarray(ids), max_new_tokens=16,
                                  eos_token_id=eos, cache_dtype=jnp.float32))
        got = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids),
                                                max_new_tokens=16, eos_token_id=eos,
                                                cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_matches_on_ring_cached_window_model(self):
        from accelerate_tpu.generation import generate, prompt_lookup_generate

        model, params, cfg = self._model(sliding_window=8)
        ids = np.tile(np.array([[5, 9]], np.int32), (1, 5))
        ref = np.asarray(generate(model, params, jnp.asarray(ids), max_new_tokens=20,
                                  cache_dtype=jnp.float32))
        got = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids),
                                                max_new_tokens=20,
                                                cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_batch_gt1_rejected(self):
        from accelerate_tpu.generation import prompt_lookup_generate

        model, params, cfg = self._model()
        with pytest.raises(ValueError, match="batch-1"):
            prompt_lookup_generate(model, params, jnp.zeros((2, 4), jnp.int32))

    def test_prompt_lengths_share_one_speculate_compile(self):
        """The speculate loop is keyed by the BUCKETED buffer length, not
        the exact prompt length — interactive use with varied prompts must
        not thrash the compile cache (one loop per 128-bucket)."""
        from accelerate_tpu import generation
        from accelerate_tpu.generation import generate, prompt_lookup_generate

        model, params, cfg = self._model()
        kw = dict(max_new_tokens=12, cache_dtype=jnp.float32)
        before = set(generation._generate_cache)
        for S in (6, 9, 14):  # all bucket to L=128
            ids = (np.arange(S, dtype=np.int32)[None] * 37 + 5) % cfg.vocab_size
            ref = np.asarray(generate(model, params, jnp.asarray(ids), **kw))
            got = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids), **kw))
            np.testing.assert_array_equal(got, ref)
        new_lookup = [k for k in set(generation._generate_cache) - before
                      if any(isinstance(p, tuple) and p and p[0] == "lookup"
                             for p in k if isinstance(p, tuple))]
        assert len(new_lookup) == 1, new_lookup


class TestAssistedGenerate:
    """Draft-model speculation must produce EXACTLY the target's generate
    output — the target's predictions decide every commit, the draft only
    proposes (transformers' assisted-generation contract)."""

    def _pair(self, **cfg_overrides):
        import dataclasses

        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False, **cfg_overrides)
        target = LlamaForCausalLM(cfg)
        tp = target.init_params(jax.random.PRNGKey(3), batch_size=1, seq_len=8)
        draft = LlamaForCausalLM(dataclasses.replace(cfg, num_hidden_layers=1))
        dp = draft.init_params(jax.random.PRNGKey(9), batch_size=1, seq_len=8)
        return target, tp, draft, dp, cfg

    def test_matches_target_greedy(self):
        from accelerate_tpu.generation import assisted_generate, generate

        target, tp, draft, dp, cfg = self._pair()
        ids = (np.arange(12, dtype=np.int32)[None] * 37 + 5) % cfg.vocab_size
        ref = np.asarray(generate(target, tp, jnp.asarray(ids), max_new_tokens=24,
                                  cache_dtype=jnp.float32))
        got = np.asarray(assisted_generate(target, tp, draft, dp, jnp.asarray(ids),
                                           max_new_tokens=24, cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)
        # Self-speculation (draft == target): every draft accepted, same result.
        got_self = np.asarray(assisted_generate(target, tp, target, tp,
                                                jnp.asarray(ids), max_new_tokens=24,
                                                cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got_self, ref)

    def test_matches_with_eos_and_window_model(self):
        from accelerate_tpu.generation import assisted_generate, generate

        target, tp, draft, dp, cfg = self._pair(sliding_window=8)
        ids = np.tile(np.array([[5, 9]], np.int32), (1, 5))
        ref_free = np.asarray(generate(target, tp, jnp.asarray(ids),
                                       max_new_tokens=20, cache_dtype=jnp.float32))
        eos = int(ref_free[0, 16])
        ref = np.asarray(generate(target, tp, jnp.asarray(ids), max_new_tokens=20,
                                  eos_token_id=eos, cache_dtype=jnp.float32))
        got = np.asarray(assisted_generate(target, tp, draft, dp, jnp.asarray(ids),
                                           max_new_tokens=20, eos_token_id=eos,
                                           cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_sampled_is_deterministic_per_seed(self):
        from accelerate_tpu.generation import assisted_generate

        target, tp, draft, dp, cfg = self._pair()
        ids = (np.arange(8, dtype=np.int32)[None] * 11 + 3) % cfg.vocab_size
        kw = dict(max_new_tokens=12, do_sample=True, top_k=8,
                  cache_dtype=jnp.float32)
        a = np.asarray(assisted_generate(target, tp, draft, dp, jnp.asarray(ids),
                                         rng=jax.random.PRNGKey(1), **kw))
        b = np.asarray(assisted_generate(target, tp, draft, dp, jnp.asarray(ids),
                                         rng=jax.random.PRNGKey(1), **kw))
        np.testing.assert_array_equal(a, b)

    @pytest.mark.parametrize("S,mnt,K", [
        (1, 8, 5),  # 1-token prompt, the nastiest boundary — stays default
        pytest.param(3, 1, 5, marks=pytest.mark.nightly),
        pytest.param(2, 2, 7, marks=pytest.mark.nightly),
        pytest.param(5, 3, 1, marks=pytest.mark.nightly),
    ])
    def test_edge_lengths_stay_exact(self, S, mnt, K):
        """One-token prompts, single-token generations, K > max_new_tokens
        (overshoot commits capped) — every corner stays target-exact."""
        from accelerate_tpu.generation import assisted_generate, generate

        target, tp, draft, dp, cfg = self._pair()
        ids = (np.arange(S, dtype=np.int32)[None] * 13 + 2) % cfg.vocab_size
        ref = np.asarray(generate(target, tp, jnp.asarray(ids), max_new_tokens=mnt,
                                  cache_dtype=jnp.float32))
        got = np.asarray(assisted_generate(target, tp, draft, dp, jnp.asarray(ids),
                                           max_new_tokens=mnt, num_draft=K,
                                           cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_input_validation(self):
        import dataclasses

        from accelerate_tpu.generation import assisted_generate
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        target, tp, draft, dp, cfg = self._pair()
        with pytest.raises(ValueError, match="batch-1"):
            assisted_generate(target, tp, draft, dp, jnp.zeros((2, 4), jnp.int32))
        other = LlamaForCausalLM(dataclasses.replace(cfg, vocab_size=cfg.vocab_size * 2))
        op = other.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        with pytest.raises(ValueError, match="share a vocabulary"):
            assisted_generate(target, tp, other, op, jnp.zeros((1, 4), jnp.int32))


class TestSpeculativeSampling:
    """do_sample speculation must be DISTRIBUTION-exact (the speculative
    sampling theorem), not just plausible."""

    def test_accept_rule_preserves_target_distribution(self):
        # K=1: whatever the draft, the law of the emitted token must be
        # exactly softmax(warped_logits[0]).
        from accelerate_tpu.generation import speculative_accept

        V = 8
        logits = jnp.asarray(np.array([
            [2.0, 0.1, -1.0, 0.5, 1.5, -0.5, 0.0, 0.7],
            [0.0] * V,
        ], np.float32))
        target = np.asarray(jax.nn.softmax(logits[0]))
        draft = jnp.asarray([4])  # a likely (but not top) token

        @jax.jit
        def one(key):
            m, final = speculative_accept(logits, draft, key)
            return jnp.where(m >= 1, draft[0], final)

        keys = jax.random.split(jax.random.PRNGKey(0), 20000)
        toks = np.asarray(jax.vmap(one)(keys))
        emp = np.bincount(toks, minlength=V) / len(toks)
        np.testing.assert_allclose(emp, target, atol=0.015)

    def test_full_acceptance_bonus_samples_target(self):
        # Draft token has ~all the mass at position 0 -> m = 1 (almost)
        # always; the bonus must then follow position 1's target.
        from accelerate_tpu.generation import speculative_accept

        V = 8
        row0 = np.full(V, -30.0, np.float32); row0[3] = 10.0
        row1 = np.array([1.0, 0.0, 2.0, -1.0, 0.5, 0.2, -0.3, 0.8], np.float32)
        logits = jnp.asarray(np.stack([row0, row1]))
        target1 = np.asarray(jax.nn.softmax(logits[1]))
        draft = jnp.asarray([3])

        @jax.jit
        def one(key):
            return speculative_accept(logits, draft, key)

        keys = jax.random.split(jax.random.PRNGKey(1), 20000)
        ms, finals = jax.vmap(one)(keys)
        assert float(np.mean(np.asarray(ms))) > 0.999
        emp = np.bincount(np.asarray(finals), minlength=V) / len(keys)
        np.testing.assert_allclose(emp, target1, atol=0.015)

    def test_tiny_temperature_degenerates_to_greedy(self):
        from accelerate_tpu.generation import generate, prompt_lookup_generate
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(5), batch_size=1, seq_len=8)
        ids = np.tile(np.array([[9, 4, 17]], np.int32), (1, 4))
        ref = np.asarray(generate(model, params, jnp.asarray(ids), max_new_tokens=18,
                                  cache_dtype=jnp.float32))
        got = np.asarray(prompt_lookup_generate(
            model, params, jnp.asarray(ids), max_new_tokens=18,
            do_sample=True, temperature=1e-6, cache_dtype=jnp.float32))
        np.testing.assert_array_equal(got, ref)

    def test_seeded_determinism(self):
        from accelerate_tpu.generation import prompt_lookup_generate
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(6), batch_size=1, seq_len=8)
        ids = (np.arange(10, dtype=np.int32)[None] * 7) % cfg.vocab_size
        kw = dict(max_new_tokens=12, do_sample=True, temperature=0.9, top_k=16,
                  cache_dtype=jnp.float32, rng=jax.random.PRNGKey(42))
        a = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids), **kw))
        b = np.asarray(prompt_lookup_generate(model, params, jnp.asarray(ids), **kw))
        np.testing.assert_array_equal(a, b)


class TestExecutableCacheLRU:
    """The module-level executable cache (generation._generate_cache) is a
    true LRU: a steadily-reused config must survive unbounded churn of
    one-shot configs — FIFO eviction would silently recompile the hot
    path every 64th request."""

    def _scoped(self):
        from accelerate_tpu import generation as g

        saved = dict(g._generate_cache)
        g._generate_cache.clear()
        return g, saved

    def test_hot_entry_survives_64_one_shot_inserts(self):
        g, saved = self._scoped()
        try:
            g._cache_put("hot", "compiled")
            for i in range(64):
                assert g._cache_get("hot") == "compiled", f"evicted at churn {i}"
                g._cache_put(("one-shot", i), i)
            assert g._cache_get("hot") == "compiled"
            assert len(g._generate_cache) <= 64
        finally:
            g._generate_cache.clear()
            g._generate_cache.update(saved)

    def test_untouched_entries_evict_oldest_first(self):
        g, saved = self._scoped()
        try:
            for i in range(64):
                g._cache_put(("cold", i), i)
            g._cache_put(("new", 0), 0)  # bound reached: ("cold", 0) goes
            assert g._cache_get(("cold", 0)) is None
            assert g._cache_get(("cold", 1)) == 1
        finally:
            g._generate_cache.clear()
            g._generate_cache.update(saved)
