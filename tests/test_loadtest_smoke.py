"""CI smoke for ``accelerate-tpu loadtest --check``.

Drives the real command end-to-end in-process — self-hosted tiny fleet,
asyncio SSE front end, open-loop arrivals, conformance report — on a
schedule small enough for the fast lane. ``--check`` is the contract:
exit 0 means zero protocol violations (non-2xx without structure,
missing Retry-After, truncated SSE, token mismatches) and balanced
gateway counters, so a regression anywhere on the serving path turns
this test red without any perf-threshold flakiness.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.commands.loadtest import (  # noqa: E402
    _parse_priorities,
    loadtest_command,
    loadtest_command_parser,
)


def test_parse_priorities():
    assert _parse_priorities("interactive=0.2,batch=0.8") == (
        ("interactive", 0.2), ("batch", 0.8))
    assert _parse_priorities(" a=1 , b=2 ") == (("a", 1.0), ("b", 2.0))
    for bad in ("", "interactive", "=0.5", "a=", "a=zero", "a=0", "a=-1"):
        with pytest.raises(SystemExit):
            _parse_priorities(bad)


def test_loadtest_check_passes_on_tiny_schedule(tmp_path):
    out = tmp_path / "report.json"
    args = loadtest_command_parser().parse_args([
        "--n-streams", "8", "--rps", "50",
        "--prompt-len", "4", "--prompt-max", "8",
        "--out-tokens", "4", "--out-max", "8",
        "--wall-deadline", "30",
        "--priorities", "interactive=0.5,batch=0.5",
        "--output", str(out),
        "--check",
    ])
    rc = loadtest_command(args)
    assert rc == 0, "loadtest --check flagged conformance violations"
    report = json.loads(out.read_text())
    assert report["goodput"]["completed"] == 8, report["goodput"]
    conf = report["conformance"]
    assert conf["token_mismatches"] == 0 and conf["truncated_sse"] == 0
    assert report["counters_balance"]
    # The declared class mix surfaces as the per-class breakdown, and
    # every stream lands in exactly one class.
    per = report["per_priority"]
    assert set(per) <= {"interactive", "batch"}
    assert sum(pr["offered"] for pr in per.values()) == 8


def test_loadtest_check_exit_code_reflects_violations(monkeypatch):
    # --check must actually gate on the report: force a violation count
    # into the built report and the command has to exit non-zero.
    from accelerate_tpu import loadgen

    real = loadgen.build_report

    def tainted(*a, **kw):
        rep = real(*a, **kw)
        rep["conformance"]["token_mismatches"] += 1
        return rep

    monkeypatch.setattr("accelerate_tpu.loadgen.build_report", tainted)
    args = loadtest_command_parser().parse_args([
        "--n-streams", "2", "--rps", "50",
        "--prompt-len", "4", "--prompt-max", "8",
        "--out-tokens", "2", "--out-max", "4",
        "--wall-deadline", "30", "--check",
    ])
    assert loadtest_command(args) == 1
