"""CI smoke for ``accelerate-tpu loadtest --check``.

Drives the real command end-to-end in-process — self-hosted tiny fleet,
asyncio SSE front end, open-loop arrivals, conformance report — on a
schedule small enough for the fast lane. ``--check`` is the contract:
exit 0 means zero protocol violations (non-2xx without structure,
missing Retry-After, truncated SSE, token mismatches) and balanced
gateway counters, so a regression anywhere on the serving path turns
this test red without any perf-threshold flakiness.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu.commands.loadtest import (  # noqa: E402
    loadtest_command,
    loadtest_command_parser,
)


def test_loadtest_check_passes_on_tiny_schedule(tmp_path):
    out = tmp_path / "report.json"
    args = loadtest_command_parser().parse_args([
        "--n-streams", "8", "--rps", "50",
        "--prompt-len", "4", "--prompt-max", "8",
        "--out-tokens", "4", "--out-max", "8",
        "--wall-deadline", "30",
        "--output", str(out),
        "--check",
    ])
    rc = loadtest_command(args)
    assert rc == 0, "loadtest --check flagged conformance violations"
    report = json.loads(out.read_text())
    assert report["goodput"]["completed"] == 8, report["goodput"]
    conf = report["conformance"]
    assert conf["token_mismatches"] == 0 and conf["truncated_sse"] == 0
    assert report["counters_balance"]


def test_loadtest_check_exit_code_reflects_violations(monkeypatch):
    # --check must actually gate on the report: force a violation count
    # into the built report and the command has to exit non-zero.
    from accelerate_tpu import loadgen

    real = loadgen.build_report

    def tainted(*a, **kw):
        rep = real(*a, **kw)
        rep["conformance"]["token_mismatches"] += 1
        return rep

    monkeypatch.setattr("accelerate_tpu.loadgen.build_report", tainted)
    args = loadtest_command_parser().parse_args([
        "--n-streams", "2", "--rps", "50",
        "--prompt-len", "4", "--prompt-max", "8",
        "--out-tokens", "2", "--out-max", "4",
        "--wall-deadline", "30", "--check",
    ])
    assert loadtest_command(args) == 1
