"""Flash-attention kernel correctness vs the einsum reference (interpret mode
on CPU; the same kernel code compiles for TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.ops.attention import _einsum_attention
from accelerate_tpu.ops.flash_pallas import pallas_flash_attention


def make_qkv(B=2, S=256, H=2, D=64, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    shape = (B, S, H, D)
    q = jax.random.normal(ks[0], shape, dtype)
    k = jax.random.normal(ks[1], shape, dtype)
    v = jax.random.normal(ks[2], shape, dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = make_qkv()
    ref = _einsum_attention(q, k, v, causal=causal)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_forward_rectangular_blocks():
    q, k, v = make_qkv(S=256)
    ref = _einsum_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=128)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_backward_matches_reference(causal):
    q, k, v = make_qkv(B=1, S=128, H=2, D=32)

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64) ** 2).sum()

    def loss_ref(q, k, v):
        return (_einsum_attention(q, k, v, causal=causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


@pytest.mark.parametrize("window", [1, 40, 64, 100])
def test_sliding_window_forward_matches_reference(window):
    """Windows off, at, and across block boundaries (blocks 64)."""
    q, k, v = make_qkv(B=1, S=256, H=2, D=32)
    ref = _einsum_attention(q, k, v, causal=True, sliding_window=window)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                 sliding_window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bq,bk,window", [(64, 128, 96), (128, 64, 200), (64, 64, 255)])
def test_sliding_window_banded_grid_rectangular(bq, bk, window):
    """The banded grid must never miss a visible block, whatever the
    block-shape/window alignment."""
    q, k, v = make_qkv(B=1, S=512, H=1, D=32, seed=3)
    ref = _einsum_attention(q, k, v, causal=True, sliding_window=window)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=bq, block_k=bk,
                                 sliding_window=window)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_sliding_window_backward_matches_reference():
    q, k, v = make_qkv(B=1, S=128, H=2, D=32)
    window = 40  # crosses the 64-wide block boundary

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                       sliding_window=window) ** 2).sum()

    def loss_ref(q, k, v):
        return (_einsum_attention(q, k, v, causal=True, sliding_window=window) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_sliding_window_requires_causal():
    q, k, v = make_qkv(B=1, S=128, H=1, D=32)
    with pytest.raises(ValueError, match="sliding_window requires causal"):
        pallas_flash_attention(q, k, v, causal=False, sliding_window=16)


def _packed_segments(B, S, seed=0):
    """Random packed layout: per-row segment ids 1,1,...,2,2,...,3..."""
    rng = np.random.default_rng(seed)
    segs = np.zeros((B, S), np.int32)
    for b in range(B):
        boundaries = np.sort(rng.choice(np.arange(8, S - 8), size=2, replace=False))
        segs[b, : boundaries[0]] = 1
        segs[b, boundaries[0]:boundaries[1]] = 2
        segs[b, boundaries[1]:] = 3
    return jnp.asarray(segs)


@pytest.mark.parametrize("causal", [True, False])
def test_segment_ids_forward_matches_reference(causal):
    """Packed sequences: cross-segment pairs masked inside the kernel —
    packing keeps flash memory asymptotics instead of the einsum fallback."""
    q, k, v = make_qkv(B=2, S=256, H=2, D=32, seed=5)
    segs = _packed_segments(2, 256, seed=5)
    ref = _einsum_attention(q, k, v, causal=causal, segment_ids=segs)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                                 segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_segment_ids_backward_matches_reference():
    q, k, v = make_qkv(B=1, S=128, H=2, D=32, seed=6)
    segs = _packed_segments(1, 128, seed=6)

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                       segment_ids=segs) ** 2).sum()

    def loss_ref(q, k, v):
        return (_einsum_attention(q, k, v, causal=True, segment_ids=segs) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_segment_ids_rectangular_blocks():
    """Segment boundaries crossing block edges, uneven block shapes."""
    q, k, v = make_qkv(B=1, S=256, H=1, D=32, seed=7)
    segs = _packed_segments(1, 256, seed=7)
    ref = _einsum_attention(q, k, v, causal=True, segment_ids=segs)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=128,
                                 segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_segment_ids_with_sliding_window_compose():
    # Packed sequences + local attention: the banded grid and the segment
    # mask must compose exactly (forward AND backward).
    q, k, v = make_qkv(B=1, S=256, H=2, D=32)
    segs = _packed_segments(1, 256)

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                       sliding_window=70, segment_ids=segs) ** 2).sum()

    def loss_ref(q, k, v):
        return (_einsum_attention(q, k, v, causal=True, sliding_window=70,
                                  segment_ids=segs) ** 2).sum()

    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                 sliding_window=70, segment_ids=segs)
    ref = _einsum_attention(q, k, v, causal=True, sliding_window=70, segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")


def test_bf16_inputs():
    q, k, v = make_qkv(dtype=jnp.bfloat16)
    ref = _einsum_attention(q, k, v, causal=True)
    out = pallas_flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


# -- GQA (narrow KV, kernels index the shared head via h // rep) -------------

def make_gqa_qkv(B=1, S=128, H=4, G=2, D=32, dtype=jnp.float32, seed=7):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, S, H, D), dtype)
    k = jax.random.normal(ks[1], (B, S, G, D), dtype)
    v = jax.random.normal(ks[2], (B, S, G, D), dtype)
    return q, k, v


def _repeat_kv(q, k, v):
    rep = q.shape[2] // k.shape[2]
    return jnp.repeat(k, rep, axis=2), jnp.repeat(v, rep, axis=2)


@pytest.mark.parametrize("causal", [True, False])
def test_gqa_forward_matches_repeated(causal):
    q, k, v = make_gqa_qkv()
    kf, vf = _repeat_kv(q, k, v)
    ref = _einsum_attention(q, kf, vf, causal=causal)
    # the grouped einsum branch itself
    ref_gqa = _einsum_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(ref_gqa), np.asarray(ref), atol=2e-5, rtol=2e-5)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_backward_matches_repeated():
    q, k, v = make_gqa_qkv()

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64) ** 2).sum()

    def loss_ref(q, kf, vf):
        return (_einsum_attention(q, kf, vf, causal=True) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    # Reference grads: expand, differentiate, group-sum dk/dv back.
    rep = q.shape[2] // k.shape[2]
    kf, vf = _repeat_kv(q, k, v)
    gq, gkf, gvf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, kf, vf)
    B, S, H, D = q.shape
    G = k.shape[2]
    # jnp.repeat on axis 2 lays heads out kv-head-major: [g0, g0, g1, g1].
    gk = gkf.reshape(B, S, G, rep, D).sum(axis=3)
    gv = gvf.reshape(B, S, G, rep, D).sum(axis=3)
    for a, b, name in zip(g_flash, (gq, gk, gv), ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"{name} mismatch")


def test_gqa_sliding_window_matches_repeated():
    q, k, v = make_gqa_qkv(S=256)
    kf, vf = _repeat_kv(q, k, v)
    ref = _einsum_attention(q, kf, vf, causal=True, sliding_window=70)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                 sliding_window=70)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_segments_match_repeated():
    q, k, v = make_gqa_qkv(S=128)
    segs = _packed_segments(1, 128)
    kf, vf = _repeat_kv(q, k, v)
    ref = _einsum_attention(q, kf, vf, causal=True, segment_ids=segs)
    out = pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                 segment_ids=segs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_gqa_rejects_indivisible_heads():
    q, k, v = make_gqa_qkv(H=4, G=3)
    with pytest.raises(ValueError, match="not a multiple"):
        pallas_flash_attention(q, k, v, causal=True)


# -- logit softcapping (Gemma2: cap * tanh(s / cap) inside the kernel) -------

@pytest.mark.parametrize("causal", [True, False])
def test_softcap_forward_matches_reference(causal):
    q, k, v = make_qkv(B=1, S=128, H=2, D=32)
    ref = _einsum_attention(q, k, v, causal=causal, logit_softcap=7.0)
    out = pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64,
                                 logit_softcap=7.0)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)
    # the cap must actually change the result
    plain = pallas_flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
    assert np.abs(np.asarray(out) - np.asarray(plain)).max() > 1e-4


def test_softcap_backward_matches_reference():
    q, k, v = make_qkv(B=1, S=128, H=2, D=32)

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, causal=True, block_q=64, block_k=64,
                                       logit_softcap=7.0) ** 2).sum()

    def loss_ref(q, k, v):
        return (_einsum_attention(q, k, v, causal=True, logit_softcap=7.0) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4,
                                   err_msg=f"d{name} mismatch")
        assert np.isfinite(np.asarray(a)).all(), f"d{name} has NaN/inf"


def test_softcap_with_window_and_gqa_backward():
    # softcap + banded grid + narrow KV + custom scale, all at once.
    q, k, v = make_gqa_qkv(S=256, H=4, G=2)

    kw = dict(causal=True, block_q=64, block_k=64, sliding_window=70,
              logit_softcap=5.0, sm_scale=0.17)

    def loss_flash(q, k, v):
        return (pallas_flash_attention(q, k, v, **kw) ** 2).sum()

    rep = 2
    kf, vf = _repeat_kv(q, k, v)

    def loss_ref(q, kf, vf):
        return (_einsum_attention(q, kf, vf, causal=True, sliding_window=70,
                                  logit_softcap=5.0, sm_scale=0.17) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gq, gkf, gvf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, kf, vf)
    B, S, H, D = q.shape
    gk = gkf.reshape(B, S, 2, rep, D).sum(axis=3)
    gv = gvf.reshape(B, S, 2, rep, D).sum(axis=3)
    for a, b, name in zip(g_flash, (gq, gk, gv), ("dq", "dk", "dv")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=7e-4, rtol=7e-4,
                                   err_msg=f"{name} mismatch")
