"""Quantized serving: int8 KV pages (per-page scales) + int8 base weights.

The acceptance-critical properties pinned here:

* OFF MEANS OFF — ``kv_dtype=None`` / ``weights_dtype=None`` engines
  trace the quantization hooks into NOTHING: the fp paged engine stays
  bit-exact vs offline ``generation.generate``.
* ZERO RECOMPILES, SAME COUNTS — an int8 engine serves warm with the
  compile listener silent and the SAME warm-executable counts as its fp
  twin (quantize-at-write / dequantize-at-read live inside the existing
  programs; alloc/free/alias/preempt stay host work on the page table).
* PREFIX-CACHE ISOLATION — a shared (fleet-style) PrefixCache never
  restores an fp entry into an int8 pool or vice versa: chunk keys are
  seeded with the kv dtype, so each engine only ever hits its own kind.
* EXACT LoRA ON A QUANTIZED BASE — with ``weights_dtype="int8"`` the
  engine's math IS offline generate over the dequantized-quantized
  params: base requests match that reference token-exactly and adapter
  requests match the merged-adapter reference on the same quantized
  base (the low-rank path rides full precision on top).
* BYTE ACCOUNTING — int8 pages cost elems + one f32 scale per leaf,
  so the pool (and everything downstream of ``_page_bytes``) shrinks.
* VALIDATION — unsupported dtypes and dense+kv_dtype combos fail fast.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.adapters import (  # noqa: E402
    AdapterBank,
    LoRAConfig,
    init_lora_params,
    merge_adapter,
    quantize_base_weights,
)
from accelerate_tpu.adapters.quantize import dequantize_params  # noqa: E402
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.serving import PrefixCache, ServingEngine  # noqa: E402
from accelerate_tpu.serving.metrics import ServingStats  # noqa: E402
from accelerate_tpu.utils.profiling import CompileWatcher  # noqa: E402

EOS = 7

PROMPTS = [
    np.array([[3, 5, 7, 11, 2]], np.int32),
    np.array([[1, 4, 9]], np.int32),
    np.array([[2, 2, 6, 1, 8, 5, 3, 9, 4, 1, 7, 6]], np.int32),
]

BASE = dict(max_slots=2, max_len=64, eos_token_id=None, prefill_chunk=8,
            prefix_cache_mb=0.0)


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


def _offline(m, params, prompt, n, eos=None):
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=eos)
    return np.asarray(out)[0, prompt.shape[1]:]


def _run(eng, prompts=PROMPTS, n=12, adapter=None):
    reqs = [eng.submit(p, max_new_tokens=n, ignore_eos=True, block=True,
                       adapter=adapter) for p in prompts]
    return [np.asarray(r.result(timeout=120)) for r in reqs]


class TestOffMeansOff:
    def test_fp_paged_engine_bit_exact_vs_offline(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, **BASE)
        assert eng.paged and eng.kv_dtype is None and eng.weights_dtype is None
        try:
            for toks, p in zip(_run(eng), PROMPTS):
                assert np.array_equal(toks, _offline(m, params, p, 12)), (
                    "kv_dtype=None must stay BIT-exact vs offline generate")
        finally:
            eng.shutdown(drain=False)


class TestZeroRecompile:
    def test_int8_kv_same_executable_counts_as_fp(self, tiny):
        _, m, params = tiny
        counts = {}
        for kv in (None, "int8"):
            eng = ServingEngine(m, params, kv_dtype=kv, **BASE)
            try:
                _run(eng)
                with CompileWatcher() as watcher:
                    _run(eng)  # warm: staggered lengths, allocs, frees
                counts[kv] = (eng._prefill_chunk._cache_size(),
                              eng._decode._cache_size())
                if kv == "int8":
                    assert not watcher.events, (
                        f"int8 engine recompiled after warmup: "
                        f"{watcher.events} — quantization must live inside "
                        "the existing programs, not fork new shapes")
            finally:
                eng.shutdown(drain=False)
        assert counts["int8"] == counts[None] == (1, 1), counts

    def test_int8_kv_speculative_one_extra_executable(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, kv_dtype="int8", draft_model=m,
                            draft_params=params, spec_tokens=4, **BASE)
        try:
            _run(eng, n=10)
            with CompileWatcher() as watcher:
                _run(eng, n=10)
            assert not watcher.events, watcher.events
            assert eng._prefill_chunk._cache_size() == 1
            assert eng._spec._cache_size() == 1
            assert eng.stats.summary()["spec_ticks"] > 0
        finally:
            eng.shutdown(drain=False)


class TestPrefixCacheIsolation:
    # 17 tokens = two full 8-token chunks worth of restorable prefix.
    PROMPT = np.arange(1, 18, dtype=np.int32)[None]

    def test_shared_cache_never_crosses_kv_dtypes(self, tiny):
        _, m, params = tiny
        shared = PrefixCache(8 * 2 ** 20)
        kw = dict(BASE)
        del kw["prefix_cache_mb"]
        fp = ServingEngine(m, params, prefix_cache=shared, **kw)
        q = ServingEngine(m, params, kv_dtype="int8", prefix_cache=shared,
                          **kw)
        try:
            ref = _offline(m, params, self.PROMPT, 8)
            # fp populates, then hits its own entry.
            a, b = (_run(fp, [self.PROMPT], n=8)[0] for _ in range(2))
            assert np.array_equal(a, ref) and np.array_equal(b, ref)
            assert fp.stats.summary()["prefix_cache_hit_chunks"] > 0
            # The int8 engine probes the SAME chunk content but must not
            # restore the fp blocks into its quantized pool...
            c = _run(q, [self.PROMPT], n=8)[0]
            assert q.stats.summary()["prefix_cache_hit_chunks"] == 0, (
                "an fp prefix entry restored into an int8 pool — chunk "
                "keys are no longer seeded with the kv dtype")
            # ...while its own (int8-keyed) entry hits on the repeat.
            d = _run(q, [self.PROMPT], n=8)[0]
            assert q.stats.summary()["prefix_cache_hit_chunks"] > 0
            assert np.array_equal(c, d)
            # And the int8 put did not clobber the fp entry either.
            before = fp.stats.summary()["prefix_cache_hit_chunks"]
            _run(fp, [self.PROMPT], n=8)
            assert fp.stats.summary()["prefix_cache_hit_chunks"] > before
        finally:
            fp.shutdown(drain=False)
            q.shutdown(drain=False)


class TestQuantizedWeights:
    def test_base_matches_offline_on_dequantized_params(self, tiny):
        _, m, params = tiny
        dq = dequantize_params(quantize_base_weights(params), jnp.float32)
        eng = ServingEngine(m, params, weights_dtype="int8", **BASE)
        try:
            for toks, p in zip(_run(eng), PROMPTS):
                assert np.array_equal(toks, _offline(m, dq, p, 12)), (
                    "weights_dtype='int8' must compute exactly offline "
                    "generate over the dequantized-quantized params")
        finally:
            eng.shutdown(drain=False)

    def test_lora_stays_exact_on_quantized_base(self, tiny):
        _, m, params = tiny
        cfg_l = LoRAConfig(rank=4)
        ad = init_lora_params(jax.random.PRNGKey(1), params, cfg_l)
        bank = AdapterBank(params, config=cfg_l, max_adapters=2)
        bank.register("a", ad)
        dq = dequantize_params(quantize_base_weights(params), jnp.float32)
        refs = {"a": merge_adapter(dq, ad), None: dq}
        eng = ServingEngine(m, params, weights_dtype="int8", adapters=bank,
                            **BASE)
        try:
            for name in ("a", None):
                for toks, p in zip(_run(eng, adapter=name), PROMPTS):
                    assert np.array_equal(
                        toks, _offline(m, refs[name], p, 12)), (
                        f"adapter={name!r} diverged on the quantized base "
                        "— the low-rank path must ride full precision "
                        "(AdapterBank row-0 identity included)")
        finally:
            eng.shutdown(drain=False)


class TestByteAccountingAndMetrics:
    def test_int8_pool_bytes_shrink_and_report_dtype(self, tiny):
        _, m, params = tiny
        fp = ServingEngine(m, params, **BASE)
        q = ServingEngine(m, params, kv_dtype="int8", **BASE)
        try:
            assert q.kv_cache_per_chip_bytes() < fp.kv_cache_per_chip_bytes()
            assert q._page_bytes < fp._page_bytes
            assert q.page_pool_metrics()["kv_dtype"] == "int8"
            assert fp.page_pool_metrics()["kv_dtype"] is None
        finally:
            fp.shutdown(drain=False)
            q.shutdown(drain=False)

    def test_logprob_drift_gauge_is_a_running_max_that_merges(self):
        a, b = ServingStats(), ServingStats()
        a.record_logprob_drift(0.01)
        a.record_logprob_drift(0.004)   # lower: must not regress the max
        b.record_logprob_drift(0.02)
        assert a.summary()["logprob_drift"] == 0.01
        a.merge(b)
        assert a.summary()["logprob_drift"] == 0.02
        assert ServingStats().summary()["logprob_drift"] == 0.0


class TestValidation:
    def test_unsupported_dtypes_fail_fast(self, tiny):
        _, m, params = tiny
        with pytest.raises(ValueError, match="kv_dtype"):
            ServingEngine(m, params, kv_dtype="int4", **BASE)
        with pytest.raises(ValueError, match="weights_dtype"):
            ServingEngine(m, params, weights_dtype="fp8", **BASE)

    def test_kv_dtype_requires_paged(self, tiny):
        _, m, params = tiny
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, params, kv_dtype="int8", paged=False, **BASE)
