"""Tests for pytree collectives/ops (reference: test_utils/scripts/test_ops.py
and tests/test_utils.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from accelerate_tpu.utils import (
    broadcast,
    broadcast_object_list,
    concatenate,
    convert_outputs_to_fp32,
    convert_to_fp32,
    find_batch_size,
    gather,
    gather_object,
    get_data_structure,
    get_shape,
    honor_type,
    initialize_tensors,
    listify,
    pad_across_processes,
    pad_input_tensors,
    recursively_apply,
    reduce,
    send_to_device,
)


def test_recursively_apply_nested():
    data = {"a": jnp.ones((2, 3)), "b": [jnp.zeros(4), (jnp.ones(1), "str")]}
    out = recursively_apply(lambda t: t + 1, data)
    assert out["a"].sum() == 12
    assert out["b"][1][1] == "str"


def test_honor_type_namedtuple():
    from collections import namedtuple

    Point = namedtuple("Point", ["x", "y"])
    p = Point(1, 2)
    out = honor_type(p, iter([3, 4]))
    assert isinstance(out, Point) and out.x == 3


def test_send_to_device():
    batch = {"x": np.ones((4, 2), dtype=np.float32), "y": np.arange(4)}
    out = send_to_device(batch, jax.devices()[0])
    assert isinstance(out["x"], jax.Array)
    assert set(out["x"].devices()) == {jax.devices()[0]}


def test_send_to_device_skip_keys():
    batch = {"x": np.ones(3), "meta": np.zeros(2)}
    out = send_to_device(batch, jax.devices()[0], skip_keys=["meta"])
    assert isinstance(out["meta"], np.ndarray)


def test_get_data_structure_roundtrip():
    data = {"a": jnp.ones((2, 3), dtype=jnp.bfloat16)}
    skel = get_data_structure(data)
    assert skel["a"].shape == (2, 3)
    out = initialize_tensors(skel)
    assert out["a"].dtype == jnp.bfloat16 and out["a"].shape == (2, 3)


def test_get_shape_and_batch_size():
    data = [jnp.ones((5, 2)), {"k": jnp.ones((5,))}]
    assert get_shape(data) == [[5, 2], {"k": [5]}]
    assert find_batch_size(data) == 5


def test_gather_single_process_identity():
    x = jnp.arange(8.0)
    assert np.allclose(gather(x), np.arange(8.0))


def test_gather_object_single():
    assert gather_object({"a": 1}) == [{"a": 1}]


def test_gather_object_flattens_sequences():
    """Reference parity (operations.py:442-446): list payloads concatenate —
    the contract gather_for_metrics(use_gather_object=True) relies on for
    ragged uneven-tail aggregation."""
    assert gather_object([1, 2, 3]) == [1, 2, 3]
    assert gather_object((4, 5)) == [4, 5]


def test_broadcast_single():
    x = {"t": jnp.ones(3)}
    out = broadcast(x)
    assert np.allclose(out["t"], 1.0)
    objs = ["a", "b"]
    assert broadcast_object_list(objs) == ["a", "b"]


def test_concatenate():
    data = [{"x": jnp.ones((2, 3))}, {"x": jnp.zeros((1, 3))}]
    out = concatenate(data)
    assert out["x"].shape == (3, 3)


def test_pad_across_processes_noop_single():
    x = jnp.ones((3, 2))
    out = pad_across_processes(x, dim=0)
    assert out.shape == (3, 2)


def test_pad_input_tensors():
    batch = {"x": jnp.arange(10).reshape(5, 2)}
    out = pad_input_tensors(batch, batch_size=5, num_processes=4)
    assert out["x"].shape == (8, 2)
    # last row repeated
    assert np.allclose(out["x"][5], out["x"][4])


def test_reduce_mean():
    x = jnp.ones((2, 2)) * 4
    out = reduce(x, "mean")
    assert np.allclose(out, 4.0)


def test_convert_to_fp32():
    data = {"a": jnp.ones(2, dtype=jnp.bfloat16), "b": jnp.ones(2, dtype=jnp.int32)}
    out = convert_to_fp32(data)
    assert out["a"].dtype == jnp.float32
    assert out["b"].dtype == jnp.int32  # non-float untouched

    fn = convert_outputs_to_fp32(lambda: jnp.ones(1, dtype=jnp.float16))
    assert fn().dtype == jnp.float32


def test_listify():
    assert listify({"a": jnp.arange(3)}) == {"a": [0, 1, 2]}


def test_find_executable_batch_size():
    from accelerate_tpu.utils import find_executable_batch_size

    attempts = []

    @find_executable_batch_size(starting_batch_size=64)
    def train(batch_size):
        attempts.append(batch_size)
        if batch_size > 16:
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to allocate")
        return batch_size

    assert train() == 16
    assert attempts == [64, 32, 16]


def test_find_executable_batch_size_non_oom_raises():
    from accelerate_tpu.utils import find_executable_batch_size

    @find_executable_batch_size(starting_batch_size=8)
    def train(batch_size):
        raise ValueError("unrelated")

    with pytest.raises(ValueError):
        train()


def test_set_seed():
    from accelerate_tpu.utils import set_seed

    s = set_seed(42)
    a = np.random.rand(3)
    set_seed(42)
    b = np.random.rand(3)
    assert np.allclose(a, b)
    assert s == 42
