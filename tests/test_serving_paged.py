"""Paged KV-cache memory manager + speculative decoding (serving engine).

The acceptance-critical properties pinned here:

* PAGED == DENSE — the paged engine changes WHERE KV rows live (a global
  page pool indexed through a per-slot page table), never what is read
  or written: every cell of the greedy/sampled/eos/adapter/failover
  matrix must be token-identical to the dense engine and to offline
  ``generation.generate``.
* ZERO RECOMPILES — page allocation, frees, preemption and prefix
  aliasing are HOST work (the table is traced integer data), so a
  warmed paged engine serves a staggered prompt-length mix with the
  compile listener silent and exactly TWO warm executables (chunk +
  decode; its private alias cache restores by page-table writes and
  compiles NO restore program).  A speculative engine adds exactly one
  more (`_spec`) and stays silent too.
* POOL EXHAUSTION — when live streams outgrow the pool, the newest
  victim is preempted back to the queue and later resumes FROM SCRATCH
  as a longer prompt; its final stream is still bit-identical.
* ALIAS PREFIX CACHE — a repeat prompt admits by bumping page refcounts
  (``prefix_alias_chunks``), never by copying KV.
* SLIDING WINDOW — pages wholly behind the attention window are freed
  mid-stream (page-lifetime policy), with no effect on the tokens.
* VALIDATION — impossible requests and incoherent constructor combos
  fail fast with actionable errors, not deadlocks or silent fallbacks.
"""

import os
import sys
import time

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.adapters import (  # noqa: E402
    AdapterBank,
    LoRAConfig,
    init_lora_params,
    merge_adapter,
)
from accelerate_tpu.adapters.lora import (  # noqa: E402
    adapter_module_paths,
    _get_path,
)
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.serving import (  # noqa: E402
    PrefixCache,
    ReplicaSet,
    RequestStatus,
    ServingEngine,
)
from accelerate_tpu.utils.profiling import CompileWatcher  # noqa: E402

EOS = 7

PROMPTS = [
    np.array([[3, 5, 7, 11, 2]], np.int32),
    np.array([[1, 4, 9]], np.int32),
    np.array([[8, 6, 4, 2, 10, 12, 14]], np.int32),
    np.array([[42]], np.int32),
]


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


def _offline(m, params, prompt, n, seed=None, eos=EOS, **kw):
    """Offline reference; ``eos=None`` mirrors the engine's ignore_eos."""
    rng = None if seed is None else jax.random.PRNGKey(seed)
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=eos, rng=rng, **kw)
    return np.asarray(out)[0, prompt.shape[1]:]


def _assert_matches_offline(got, ref, n):
    """Engine stops AT eos; offline keeps the shape and pads with eos."""
    got = np.asarray(got)
    assert np.array_equal(got, ref[: len(got)]), (got, ref)
    if len(got) < n:
        assert got[-1] == EOS and np.all(ref[len(got):] == EOS), (got, ref)


def _nonzero_adapter(params, rank, seed):
    ad = init_lora_params(jax.random.PRNGKey(seed), params,
                          LoRAConfig(rank=rank))
    for i, dotted in enumerate(adapter_module_paths(ad)):
        mod = _get_path(ad, dotted)
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 997), i)
        mod["b"] = 0.05 * jax.random.normal(k, mod["b"].shape, mod["b"].dtype)
    return ad


class TestPagedVsDenseExactness:
    """Greedy and sampled streams from the paged engine must be
    bit-identical to the dense (``paged=False``) engine and offline."""

    N = 24

    @pytest.fixture(scope="class")
    def engines(self, tiny):
        _, m, params = tiny
        kw = dict(max_slots=3, max_len=64, eos_token_id=EOS,
                  prefill_chunk=8, prefix_cache_mb=0.0)
        engs = {"paged": ServingEngine(m, params, **kw),  # paged=None -> True
                "dense": ServingEngine(m, params, paged=False, **kw)}
        assert engs["paged"].paged and not engs["dense"].paged
        yield engs
        for e in engs.values():
            if e.running:
                e.shutdown(drain=False)

    @pytest.mark.parametrize("seed", [None, 11])
    def test_matrix_matches_dense_and_offline(self, tiny, engines, seed):
        _, m, params = tiny
        refs = [_offline(m, params, p, self.N, seed=seed) for p in PROMPTS]
        outs = {}
        for name, eng in engines.items():
            reqs = []
            for p in PROMPTS:  # staggered: joins exercise the page table
                reqs.append(eng.submit(p, max_new_tokens=self.N, seed=seed))
                time.sleep(0.01)
            outs[name] = [np.asarray(r.result(timeout=120)) for r in reqs]
        for got_p, got_d, ref in zip(outs["paged"], outs["dense"], refs):
            assert np.array_equal(got_p, got_d), (got_p, got_d)
            _assert_matches_offline(got_p, ref, self.N)

    def test_eos_latch_paged(self, tiny, engines):
        """A stream that hits EOS mid-flight stops exactly where offline
        latches, with the request's pages released back to the pool."""
        _, m, params = tiny
        eng = engines["paged"]
        free0 = eng.free_pages
        prompt = np.array([[EOS, 3, EOS, 5]], np.int32)
        r = eng.submit(prompt, max_new_tokens=self.N)
        got = r.result(timeout=120)
        _assert_matches_offline(got, _offline(m, params, prompt, self.N),
                                self.N)
        deadline = time.monotonic() + 10
        while eng.free_pages < free0 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.free_pages == free0, "retired request leaked pages"

    def test_adapters_on_paged_engine(self, tiny):
        """Multi-tenant LoRA over the paged pool: each stream matches
        offline generate under its tenant's MERGED weights."""
        _, m, params = tiny
        ad = _nonzero_adapter(params, rank=4, seed=5)
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        bank.register("a", ad)
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8, adapters=bank)
        assert eng.paged
        try:
            n = 16
            refs = {"a": merge_adapter(params, ad), None: params}
            reqs = [(name, eng.submit(p, max_new_tokens=n, adapter=name))
                    for name, p in zip(["a", None, "a"], PROMPTS)]
            for (name, r), p in zip(reqs, PROMPTS):
                _assert_matches_offline(r.result(timeout=120),
                                        _offline(m, refs[name], p, n), n)
        finally:
            eng.shutdown(drain=False)

    def test_failover_streams_stay_token_exact(self, tiny):
        """Killing a replica mid-stream: survivors re-serve the moved
        requests from scratch on their own page pools, bit-identically."""
        _, m, params = tiny
        import bench

        sleepy = bench._sleepy_llama_cls(step_ms=15.0)(LlamaConfig.tiny(
            use_flash_attention=False))
        rs = ReplicaSet.from_factory(
            lambda: ServingEngine(sleepy, params, max_slots=4, max_len=64,
                                  eos_token_id=EOS, prefill_chunk=16), 2)
        assert all(r.engine.paged for r in rs._replicas)
        n = 24
        refs = [_offline(sleepy, params, p, n) for p in PROMPTS]
        try:
            reqs = [rs.submit(p, max_new_tokens=n) for p in PROMPTS]
            deadline = time.monotonic() + 60
            while (min(len(r.tokens) for r in reqs) < 3
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert min(len(r.tokens) for r in reqs) >= 3, "streams stalled"
            victim = reqs[0].replica_trail[0]
            rs.kill_replica(victim)
            for r in reqs:
                assert r.wait(timeout=120)
            for r, ref in zip(reqs, refs):
                assert r.status is RequestStatus.COMPLETED
                _assert_matches_offline(r.tokens, ref, n)
            assert any(r.replica_trail[0] == victim for r in reqs)
        finally:
            rs.shutdown()


class TestZeroRecompilePaged:
    def test_paged_steady_state_is_two_executables(self, tiny):
        """Admitting/retiring a staggered prompt-length mix — including a
        repeat prompt restored by page-table ALIASING — must run only
        the warm chunk + decode executables: page allocation is host
        work, and the private paged prefix cache compiles no restore
        program at all."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=4.0)
        rng = np.random.default_rng(9)
        long = rng.integers(0, 256, size=(1, 33)).astype(np.int32)
        try:
            with CompileWatcher() as watcher:
                reqs = []
                # tail repeat of the multi-chunk prompt -> alias restore
                for p in PROMPTS + [long, long]:
                    reqs.append(eng.submit(p, max_new_tokens=6, seed=3))
                    time.sleep(0.01)
                for r in reqs:
                    r.result(timeout=120)
        finally:
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — paging must "
            "move page-table CONTENTS, never program shapes")
        assert eng._prefill_chunk._cache_size() == 1
        assert eng._restore_prefix is None  # alias restores are host writes
        assert eng._decode._cache_size() == 1
        assert eng.stats.summary()["prefix_alias_chunks"] >= 1

    def test_speculative_adds_exactly_one_executable(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0,
                            draft_model=m, draft_params=params,
                            spec_tokens=4)
        try:
            with CompileWatcher() as watcher:
                reqs = []
                for p in PROMPTS:
                    reqs.append(eng.submit(p, max_new_tokens=8))
                    time.sleep(0.01)
                for r in reqs:
                    r.result(timeout=120)
        finally:
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — draft length "
            "and acceptance count are data, not shapes")
        assert eng._prefill_chunk._cache_size() == 1
        assert eng._spec._cache_size() == 1
        # a spec engine never runs the plain decode tick — every decode
        # goes through _spec, so _decode stays cold (<= 1 from warmup).
        assert eng._decode._cache_size() <= 1


class TestPoolExhaustionPreemption:
    def test_preempted_stream_resumes_token_exact(self, tiny):
        """Two streams whose worst-case footprints each fit the pool but
        together exceed it: the engine must preempt (not deadlock, not
        corrupt) and the loser's final stream — re-served from scratch
        as a longer prompt — must stay bit-identical to offline."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0, max_pages=10)
        n = 40
        try:
            assert eng.total_pages == 10
            refs = [_offline(m, params, p, n, eos=None)
                    for p in PROMPTS[:2]]
            reqs = [eng.submit(p, max_new_tokens=n, ignore_eos=True)
                    for p in PROMPTS[:2]]
            for r, ref in zip(reqs, refs):
                got = np.asarray(r.result(timeout=180))
                assert np.array_equal(got, ref), (got, ref)
            s = eng.stats.summary()
            assert s["preemptions"] >= 1, (
                "10 pages cannot hold two 6-page streams; the engine must "
                f"have preempted (stats: {s})")
            assert eng.page_pool_metrics()["preemptions"] >= 1
        finally:
            eng.shutdown(drain=False)


class TestAliasPrefixCache:
    def test_repeat_prompt_admits_by_refcount(self, tiny):
        """Paged prefix hits bump page refcounts instead of copying KV:
        the repeat admission reports alias chunks and the two streams
        are bit-identical."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=96,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=4.0)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 256, size=(1, 33)).astype(np.int32)
        try:
            a = np.asarray(eng.submit(prompt, max_new_tokens=8,
                                      ignore_eos=True).result(timeout=120))
            b = np.asarray(eng.submit(prompt, max_new_tokens=8,
                                      ignore_eos=True).result(timeout=120))
            assert np.array_equal(a, b)
            s = eng.stats.summary()
            # 33 tokens = 4 full chunks of 8; all restorable by aliasing.
            assert s["prefix_alias_chunks"] >= 2, s
            assert s["prefix_cache_hit_chunks"] >= 2, s
        finally:
            eng.shutdown(drain=False)

    def test_external_cache_keeps_host_copy_path(self, tiny):
        """An EXTERNAL (fleet-shared) PrefixCache still stores host-copy
        blocks — slice-portable — and the paged engine compiles the
        restore executable for it."""
        _, m, params = tiny
        shared = PrefixCache(4 * 1024 * 1024)
        eng = ServingEngine(m, params, max_slots=2, max_len=96,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache=shared)
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 256, size=(1, 24)).astype(np.int32)
        try:
            a = np.asarray(eng.submit(prompt, max_new_tokens=8,
                                      ignore_eos=True).result(timeout=120))
            b = np.asarray(eng.submit(prompt, max_new_tokens=8,
                                      ignore_eos=True).result(timeout=120))
            assert np.array_equal(a, b)
            assert eng._restore_prefix is not None
            assert eng.stats.summary()["prefix_cache_hit_chunks"] >= 2
        finally:
            eng.shutdown(drain=False)


class TestSlidingWindowPageLifetime:
    def test_windowed_model_frees_dead_pages(self, tiny):
        """With a uniform sliding window, a page whose last position falls
        wholly behind the window can never be attended again — the
        engine drops it mid-stream.  Tokens must still match offline
        (the window MASK, not page residency, defines the math)."""
        _, _, params = tiny
        cfg = LlamaConfig.tiny(use_flash_attention=False, sliding_window=16)
        m = LlamaForCausalLM(cfg)
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0)
        assert eng._page_window == 16
        n = 40
        prompt = np.array([[3, 5, 7, 11, 2, 8, 6, 4]], np.int32)
        peak = []
        try:
            r = eng.submit(prompt, max_new_tokens=n, ignore_eos=True,
                           on_token=lambda t: peak.append(
                               eng.page_pool_metrics()["pages_used"]))
            got = np.asarray(r.result(timeout=120))
            ref = _offline(m, params, prompt, n, eos=None)
            assert np.array_equal(got, ref), (got, ref)
            # 8 + 40 = 48 positions = 6 pages of 8 if nothing were freed;
            # a 16-token window keeps at most 3 live (+1 being written).
            assert max(peak) <= 4, peak
        finally:
            eng.shutdown(drain=False)


class TestSpeculativeDecoding:
    def test_spec_streams_are_token_identical(self, tiny):
        """Greedy speculative output must be bit-identical to the plain
        engine and offline — acceptance only SKIPS ticks, never changes
        tokens — including the eos latch, and must actually accept."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0,
                            draft_model=m, draft_params=params,
                            spec_tokens=4)
        n = 24
        try:
            refs = [_offline(m, params, p, n) for p in PROMPTS]
            reqs = []
            for p in PROMPTS:
                reqs.append(eng.submit(p, max_new_tokens=n))
                time.sleep(0.01)
            for r, ref in zip(reqs, refs):
                _assert_matches_offline(r.result(timeout=120), ref, n)
            s = eng.stats.summary()
            assert s["spec_ticks"] > 0 and s["spec_accepted_tokens"] > 0, s
            assert s["spec_tokens_per_tick"] > 1.0, (
                "speculation must commit more than one token per verify "
                f"on average (stats: {s})")
        finally:
            eng.shutdown(drain=False)

    def test_spec_validation(self, tiny):
        """Only structural impossibilities reject now: the sampled /
        adapter / prefix-cache / mesh gates of PR 7 are gone (that lift
        is this PR's point) and must NOT raise."""
        _, m, params = tiny
        spec = dict(draft_model=m, draft_params=params)
        with pytest.raises(NotImplementedError, match="paged"):
            ServingEngine(m, params, paged=False, prefill_chunk=8,
                          autostart=False, warmup=False, **spec)
        with pytest.raises(ValueError, match="spec_tokens"):
            ServingEngine(m, params, prefill_chunk=8, spec_tokens=0,
                          autostart=False, warmup=False, **spec)
        with pytest.raises(ValueError, match="mutually exclusive"):
            ServingEngine(m, params, prefill_chunk=8, spec_lookup=3,
                          autostart=False, warmup=False, **spec)
        with pytest.raises(ValueError, match="spec_lookup"):
            ServingEngine(m, params, prefill_chunk=8, spec_lookup=0,
                          autostart=False, warmup=False)
        # Previously-rejected configurations now construct cleanly.
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=2)
        for kw in (dict(do_sample=True, temperature=0.8),
                   dict(adapters=bank),
                   dict(prefix_cache=PrefixCache(1024 * 1024))):
            eng = ServingEngine(m, params, prefill_chunk=8, autostart=False,
                                warmup=False, **spec, **kw)
            assert eng._spec_mode == "draft"
        eng = ServingEngine(m, params, prefill_chunk=8, spec_lookup=3,
                            autostart=False, warmup=False)
        assert eng._spec_mode == "lookup"


class TestUniversalSpeculation:
    """The exactness matrix for the universal ``_spec`` executable: each
    previously-rejected mode (sampled, adapter tenant, prefix-cache,
    draft-free prompt lookup — tp=2 lives in test_serving_mesh.py) must
    emit exactly what its non-speculative twin emits, and the whole
    matrix must run through ONE warm ``_spec`` program with the compile
    listener silent."""

    N = 24
    BASE = dict(max_slots=3, max_len=64, eos_token_id=EOS, prefill_chunk=8,
                prefix_cache_mb=0.0)
    # Spans one-chunk and multi-chunk admission; avoids EOS.
    LONG = np.arange(1, 20, dtype=np.int32)[None] % 6 + 8

    def _run(self, eng, prompts=PROMPTS, **kw):
        reqs = []
        for p in prompts:
            reqs.append(eng.submit(p, max_new_tokens=self.N, **kw))
            time.sleep(0.01)
        return [np.asarray(r.result(timeout=120)) for r in reqs]

    def _pair(self, m, params, spec_kw, base_kw=None, **submit_kw):
        """(spec streams, non-spec streams) over the same traffic."""
        base_kw = dict(self.BASE, **(base_kw or {}))
        prompts = submit_kw.pop("prompts", PROMPTS)
        e1 = ServingEngine(m, params, **base_kw, **spec_kw)
        e0 = ServingEngine(m, params, **base_kw)
        try:
            a = self._run(e1, prompts=prompts, **submit_kw)
            b = self._run(e0, prompts=prompts, **submit_kw)
            assert e1.stats.summary()["spec_ticks"] > 0
        finally:
            e1.shutdown(drain=False)
            e0.shutdown(drain=False)
        return a, b

    def test_sampled_spec_is_exact_when_determinized(self, tiny):
        """do_sample + top_k=1 concentrates the warped law on one token,
        so the rejection-sampling accept path (the SAMPLED branch of
        speculative_emit, not the greedy one) must reproduce the dense
        sampled stream bit-exactly — any drift is an accept-rule or
        rng-discipline bug that randomness would have hidden."""
        _, m, params = tiny
        a, b = self._pair(m, params,
                          dict(draft_model=m, draft_params=params,
                               spec_tokens=4),
                          base_kw=dict(do_sample=True, top_k=1), seed=3)
        for x, y in zip(a, b):
            assert np.array_equal(x, y), (x, y)

    def test_sampled_spec_is_seed_deterministic(self, tiny):
        """With temperature the spec stream cannot be compared token-wise
        to the dense one (same law, different rng consumption), but a
        fixed per-request seed must still make it reproducible: the
        per-slot rng rows split exactly once per verify tick."""
        _, m, params = tiny
        kw = dict(self.BASE, do_sample=True, temperature=0.8,
                  draft_model=m, draft_params=params, spec_tokens=4)
        outs = []
        for _ in range(2):
            eng = ServingEngine(m, params, **kw)
            try:
                outs.append(self._run(eng, seed=5))
            finally:
                eng.shutdown(drain=False)
        for x, y in zip(*outs):
            assert np.array_equal(x, y), (x, y)

    def test_adapter_spec_matches_nonspec(self, tiny):
        """A tenant's speculative stream equals its non-speculative one:
        the per-slot adapter row gathers inside the verify while the
        draft stays base-weight (proposals steer acceptance, never the
        emitted law)."""
        _, m, params = tiny
        ad = _nonzero_adapter(params, rank=4, seed=1)
        banks = []
        for _ in range(2):
            bank = AdapterBank(params, config=LoRAConfig(rank=4),
                               max_adapters=2)
            bank.register("t1", ad)
            banks.append(bank)
        e1 = ServingEngine(m, params, adapters=banks[0], **self.BASE,
                           draft_model=m, draft_params=params, spec_tokens=4)
        e0 = ServingEngine(m, params, adapters=banks[1], **self.BASE)
        try:
            a = self._run(e1, adapter="t1") + self._run(e1)  # tenant + base
            b = self._run(e0, adapter="t1") + self._run(e0)
        finally:
            e1.shutdown(drain=False)
            e0.shutdown(drain=False)
        for x, y in zip(a, b):
            assert np.array_equal(x, y), (x, y)

    def test_prefix_hit_spec_matches_cold(self, tiny):
        """A prefix-cache engine speculates: the alias-restored slot's
        draft KV is rebuilt by the draft-only chunk program, and both the
        cold and the hit stream equal the non-speculative stream."""
        _, m, params = tiny
        kw = dict(max_slots=3, max_len=64, eos_token_id=EOS,
                  prefill_chunk=8)
        e1 = ServingEngine(m, params, prefix_cache_mb=4.0, **kw,
                           draft_model=m, draft_params=params, spec_tokens=4)
        e0 = ServingEngine(m, params, prefix_cache_mb=0.0, **kw)
        try:
            cold = self._run(e1, prompts=[self.LONG])
            hit = self._run(e1, prompts=[self.LONG])
            ref = self._run(e0, prompts=[self.LONG])
            s = e1.stats.summary()
            assert s["prefix_alias_chunks"] >= 1, s
        finally:
            e1.shutdown(drain=False)
            e0.shutdown(drain=False)
        assert np.array_equal(cold[0], ref[0]), (cold, ref)
        assert np.array_equal(hit[0], ref[0]), (hit, ref)

    def test_lookup_spec_matches_nonspec(self, tiny):
        """Draft-free prompt-lookup speculation: host n-gram proposals
        through the verify-only program, token-identical to plain greedy
        even when every proposal is a miss."""
        _, m, params = tiny
        rep = np.array([[4, 5, 6, 4, 5, 6, 4, 5, 6, 4, 5]], np.int32)
        a, b = self._pair(m, params, dict(spec_lookup=2, spec_tokens=4),
                          prompts=PROMPTS + [rep])
        for x, y in zip(a, b):
            assert np.array_equal(x, y), (x, y)

    def test_universal_spec_zero_recompiles(self, tiny):
        """One engine wearing EVERY lifted constraint at once — sampling
        (top_k=1), an adapter bank, an alias prefix cache, paged draft KV
        — serves mixed traffic (tenant + base, cold + prefix-hit) through
        ONE warm ``_spec`` and ONE warm draft-rebuild program, compile
        listener silent: adapter rows, page tables, proposals, and
        acceptance counts are all data, never shapes."""
        _, m, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4),
                           max_adapters=2)
        bank.register("t1", _nonzero_adapter(params, rank=4, seed=1))
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=4.0, adapters=bank,
                            do_sample=True, top_k=1,
                            draft_model=m, draft_params=params,
                            spec_tokens=4)
        try:
            with CompileWatcher() as watcher:
                self._run(eng, prompts=[self.LONG], seed=0)
                self._run(eng, prompts=[self.LONG], seed=0)  # prefix hit
                self._run(eng, adapter="t1", seed=1)
            assert eng._spec._cache_size() == 1
            assert eng._draft_chunk._cache_size() == 1
            assert eng._prefill_chunk._cache_size() == 1
            s = eng.stats.summary()
            assert s["spec_ticks"] > 0 and s["prefix_alias_chunks"] >= 1, s
        finally:
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — adapter "
            "rows, draft pages, and acceptance are data, not shapes")


class TestPagedValidation:
    def test_constructor_combos(self, tiny):
        _, m, params = tiny
        with pytest.raises(ValueError, match="chunked prefill"):
            ServingEngine(m, params, paged=True, prefill_chunk=None,
                          autostart=False, warmup=False)
        with pytest.raises(ValueError, match="divide"):
            ServingEngine(m, params, prefill_chunk=8, page_size=3,
                          autostart=False, warmup=False)
        with pytest.raises(ValueError, match="paged"):
            ServingEngine(m, params, paged=False, prefill_chunk=8,
                          page_size=8, autostart=False, warmup=False)
        with pytest.raises(ValueError, match="max_pages"):
            ServingEngine(m, params, prefill_chunk=8, max_pages=0,
                          autostart=False, warmup=False)

    def test_submit_rejects_unsatisfiable_footprint(self, tiny):
        """A lone request whose worst case exceeds the whole pool could
        never be scheduled — submit must refuse it synchronously."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8, max_pages=4,
                            warmup=False)
        try:
            with pytest.raises(ValueError, match="KV pages"):
                eng.submit(PROMPTS[0], max_new_tokens=40)
        finally:
            eng.shutdown(drain=False)


class TestPageAwareRouting:
    """The router folds KV-page headroom into the least-loaded score
    (``ReplicaSet._candidates`` via ``engine.page_deficit``): with slots
    and load equal, a replica whose pool cannot cover a request's worst-
    case footprint loses the tie-break — long prompts route around page
    pressure instead of forcing a preemption on arrival."""

    def _paged_fleet(self, tiny, n=2):
        _, m, params = tiny
        return ReplicaSet.from_factory(
            lambda: ServingEngine(m, params, max_slots=2, max_len=64,
                                  eos_token_id=EOS, prefill_chunk=8,
                                  prefix_cache_mb=0.0, max_pages=10), n)

    def test_page_starved_replica_loses_tie_break(self, tiny):
        rs = self._paged_fleet(tiny)
        taken = []
        try:
            e0 = rs.engine(0)
            # Both replicas idle: equal free slots, equal load. Starve
            # replica 0's pool down to one page (held from the test
            # thread; the idle engine allocates nothing meanwhile).
            while e0._pool.free_pages > 1:
                taken.append(e0._pool.alloc())

            total = int(PROMPTS[2].shape[1]) + 30  # 37 tokens -> 5 pages
            assert e0.page_deficit(total) > 0
            assert rs.engine(1).page_deficit(total) == 0
            order = [r.index for r in rs._candidates(total_tokens=total)]
            assert order == [1, 0], order

            # Un-starve: with page headroom equal again, the stable index
            # tie-break puts replica 0 back in front.
            while taken:
                e0._pool.decref(taken.pop())
            order = [r.index for r in rs._candidates(total_tokens=total)]
            assert order == [0, 1], order

            # End to end: re-starve and submit the long request — it must
            # land on (and stay on) the page-rich replica.
            while e0._pool.free_pages > 1:
                taken.append(e0._pool.alloc())
            req = rs.submit(PROMPTS[2], max_new_tokens=30, ignore_eos=True)
            req.wait(timeout=120)
            assert req.replica_trail == [1], req.replica_trail
        finally:
            while taken:
                rs.engine(0)._pool.decref(taken.pop())
            rs.shutdown(drain=False)

    def test_draft_spec_engine_reports_doubled_page_footprint(self, tiny):
        """A draft-speculating replica holds TWO pages per covered page
        span (target + draft columns of the same pool), so its
        ``page_deficit`` must report the doubled footprint — otherwise
        the router over-admits it and the admission gate preempts on
        arrival. Lookup engines carry no draft KV and report 1x."""
        _, m, params = tiny
        kw = dict(max_slots=2, max_len=64, eos_token_id=EOS,
                  prefill_chunk=8, prefix_cache_mb=0.0, max_pages=10,
                  autostart=False, warmup=False)
        plain = ServingEngine(m, params, **kw)
        spec = ServingEngine(m, params, draft_model=m, draft_params=params,
                             spec_tokens=4, **kw)
        lookup = ServingEngine(m, params, spec_lookup=2, spec_tokens=4,
                               **kw)
        try:
            total = 44  # -> 6 pages of 8; 12 with the draft factor
            assert plain._spec_page_factor == 1
            assert lookup._spec_page_factor == 1
            assert spec._spec_page_factor == 2
            assert plain.page_deficit(total) == 0
            assert lookup.page_deficit(total) == 0
            assert spec.page_deficit(total) == 2  # 12 needed, 10 free
        finally:
            for e in (plain, spec, lookup):
                e.shutdown(drain=False)
