"""Multi-tenant LoRA adapter subsystem (accelerate_tpu.adapters).

The acceptance-critical properties pinned here:

* EXACTNESS — a request served under adapter X through the batched bank
  path (``((x @ a) @ b) * scale`` gathered per slot inside the compiled
  forward) is token-identical to offline ``generation.generate`` on
  ``merge_adapter(base, X)`` weights, for rank 4 and rank 8 adapters,
  greedy and sampled, including eos semantics — even when the base
  (slot-0 identity) and two different tenants share one decode batch.
* BASE UNCHANGED — slot 0 is the all-zero identity adapter whose delta
  is exactly 0.0, so base-model requests through a bank-equipped engine
  match a bank-less engine bit for bit.
* ZERO RECOMPILES — registering, hot-loading, and evicting adapters
  mid-serve triggers no new XLA compilation: the bank's shape is fixed,
  row loads run one pre-compiled dynamic_update_slice program, and
  membership changes are data, never program shapes.
* TENANT ISOLATION — the prefix KV cache is keyed by adapter identity:
  tenant A's warm prefix is a MISS for tenant B (the KV bytes differ —
  reusing them would leak A's activations into B's stream).
* LIFECYCLE — LRU residency with in-flight pinning: eviction never
  touches a row a live request is decoding from; when every row is
  pinned, admission fails that request with the retryable
  ``AdapterBankFull`` without killing the engine.
* TRAINING/CHECKPOINT — ``prepare_lora`` + ``optax.masked`` trains only
  the low-rank factors (frozen base bit-unchanged), and
  ``save_adapter``/``load_adapter`` round-trips the few-MB tree.
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.adapters import (  # noqa: E402
    AdapterBank,
    AdapterBankFull,
    LoRAConfig,
    UnknownAdapterError,
    init_lora_params,
    load_adapter,
    merge_adapter,
    prepare_lora,
    save_adapter,
)
from accelerate_tpu.adapters.lora import (  # noqa: E402
    adapter_module_paths,
    adapter_rank,
    count_lora_params,
    lora_delta,
    pad_adapter,
    target_paths,
    _get_path,
)
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.serving import ServingEngine  # noqa: E402
from accelerate_tpu.utils.profiling import CompileWatcher  # noqa: E402

EOS = 7


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


def _nonzero_adapter(params, rank, seed):
    """A rank-``rank`` adapter whose delta is NOT zero (fresh init has
    b = 0, which would make every tenant indistinguishable from base)."""
    ad = init_lora_params(jax.random.PRNGKey(seed), params,
                         LoRAConfig(rank=rank))
    for i, dotted in enumerate(adapter_module_paths(ad)):
        mod = _get_path(ad, dotted)
        k = jax.random.fold_in(jax.random.PRNGKey(seed + 997), i)
        mod["b"] = 0.05 * jax.random.normal(k, mod["b"].shape, mod["b"].dtype)
    return ad


def _offline(m, params, prompt, n, seed=None, **kw):
    rng = None if seed is None else jax.random.PRNGKey(seed)
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=EOS, rng=rng, **kw)
    return np.asarray(out)[0, prompt.shape[1]:]


def _assert_matches_offline(got, ref, n):
    got = np.asarray(got)
    assert np.array_equal(got, ref[: len(got)]), (got, ref)
    if len(got) < n:
        assert got[-1] == EOS and np.all(ref[len(got):] == EOS), (got, ref)


# ---------------------------------------------------------------------------
# core: config / init / merge / pad
# ---------------------------------------------------------------------------
class TestLoRACore:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            LoRAConfig(rank=0)
        with pytest.raises(ValueError):
            LoRAConfig(dropout=1.0)
        with pytest.raises(ValueError):
            LoRAConfig(target_modules=())
        assert LoRAConfig(rank=8, alpha=16.0).scale == 2.0

    def test_init_shapes_and_zero_delta(self, tiny):
        _, _, params = tiny
        cfg = LoRAConfig(rank=4)
        ad = init_lora_params(jax.random.PRNGKey(0), params, cfg)
        paths = adapter_module_paths(ad)
        assert paths == target_paths(params, cfg)
        assert adapter_rank(ad) == 4
        for dotted in paths:
            mod = _get_path(ad, dotted)
            kernel = _get_path(params, dotted)["kernel"]
            assert mod["a"].shape == (kernel.shape[0], 4)
            assert mod["b"].shape == (4, kernel.shape[1])
            assert np.all(np.asarray(mod["b"]) == 0.0)
            # b = 0 => the initial delta is exactly zero.
            x = jnp.ones((2, kernel.shape[0]))
            assert np.all(np.asarray(lora_delta(x, mod)) == 0.0)

    def test_unmatched_targets_raise(self, tiny):
        _, _, params = tiny
        with pytest.raises(ValueError, match="matched nothing"):
            target_paths(params, LoRAConfig(target_modules=("nope_proj",)))

    def test_merge_matches_split_application(self, tiny):
        """Merged weights and the pure low-rank path compute the same
        function (up to float addition order): logits agree to ~1e-5 and
        the argmax chain agrees exactly."""
        _, m, params = tiny
        ad = _nonzero_adapter(params, 4, seed=3)
        ids = np.array([[3, 5, 2, 9, 11]], np.int32)
        merged = m.apply({"params": merge_adapter(params, ad)}, ids)
        split = m.apply({"params": params}, ids, lora=ad)
        np.testing.assert_allclose(np.asarray(merged), np.asarray(split),
                                   atol=1e-4, rtol=1e-4)
        assert np.array_equal(np.argmax(np.asarray(merged), -1),
                              np.argmax(np.asarray(split), -1))

    def test_pad_adapter_is_bit_exact(self, tiny):
        _, m, params = tiny
        ad = _nonzero_adapter(params, 4, seed=5)
        padded = pad_adapter(ad, 8)
        assert adapter_rank(padded) == 8
        ids = np.array([[3, 5, 2, 9]], np.int32)
        out = m.apply({"params": params}, ids, lora=ad)
        out_p = m.apply({"params": params}, ids, lora=padded)
        # Zero-padding adds exact-zero partial products: bitwise equal.
        assert np.array_equal(np.asarray(out), np.asarray(out_p))
        with pytest.raises(ValueError, match="exceeds bank rank"):
            pad_adapter(padded, 4)

    def test_count_lora_params(self, tiny):
        _, m, params = tiny
        abstract = jax.eval_shape(lambda: params)
        n, nbytes = count_lora_params(abstract, LoRAConfig(rank=8))
        expect = sum(
            k.shape[0] * 8 + 8 * k.shape[1]
            for k in (_get_path(params, p)["kernel"]
                      for p in target_paths(params, LoRAConfig(rank=8))))
        assert (n, nbytes) == (expect, expect * 4)


# ---------------------------------------------------------------------------
# training split
# ---------------------------------------------------------------------------
class TestPrepareLora:
    def test_masked_step_trains_only_adapter(self, tiny):
        _, m, params = tiny
        ts = prepare_lora(m, params, LoRAConfig(rank=4),
                          rng=jax.random.PRNGKey(1))
        tx = ts.wrap_optimizer(optax.adamw(1e-2))
        train = ts.train_params()
        opt_state = tx.init(train)
        ids = np.array([[3, 5, 2, 9, 11, 4]], np.int32)

        def loss_fn(train):
            logits = m.apply({"params": train["base"]}, ids,
                             lora=train["lora"])
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        grads = jax.grad(loss_fn)(train)
        updates, _ = tx.update(grads, opt_state, train)
        new = optax.apply_updates(train, updates)

        # Frozen base: bit-identical after the step.
        for old, upd in zip(jax.tree_util.tree_leaves(train["base"]),
                            jax.tree_util.tree_leaves(new["base"])):
            assert np.array_equal(np.asarray(old), np.asarray(upd))
        # Adapter b factors move off zero; scale stays a frozen knob.
        moved = 0
        for dotted in adapter_module_paths(new["lora"]):
            mod = _get_path(new["lora"], dotted)
            old = _get_path(train["lora"], dotted)
            assert np.array_equal(np.asarray(mod["scale"]),
                                  np.asarray(old["scale"]))
            if not np.array_equal(np.asarray(mod["b"]), np.asarray(old["b"])):
                moved += 1
        assert moved > 0


# ---------------------------------------------------------------------------
# checkpoint round-trip
# ---------------------------------------------------------------------------
class TestAdapterCheckpoint:
    def test_save_load_round_trip(self, tiny, tmp_path):
        _, _, params = tiny
        cfg = LoRAConfig(rank=4, alpha=8.0)
        ad = _nonzero_adapter(params, 4, seed=9)
        save_adapter(ad, tmp_path / "ad", config=cfg)
        loaded, meta = load_adapter(tmp_path / "ad")
        assert meta["rank"] == 4
        assert meta["alpha"] == 8.0
        assert sorted(meta["modules"]) == adapter_module_paths(ad)
        assert adapter_module_paths(loaded) == adapter_module_paths(ad)
        for a, b in zip(jax.tree_util.tree_leaves(ad),
                        jax.tree_util.tree_leaves(loaded)):
            assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_load_rejects_non_adapter_dir(self, tmp_path):
        with pytest.raises((FileNotFoundError, ValueError)):
            load_adapter(tmp_path / "nothing-here")


# ---------------------------------------------------------------------------
# bank residency units
# ---------------------------------------------------------------------------
class TestAdapterBank:
    def test_row0_reserved_and_capacity(self, tiny):
        _, _, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        assert bank.capacity == 2
        with pytest.raises(ValueError, match=">= 2"):
            AdapterBank(params, max_adapters=1)
        # Row 0 is the identity: all-zero leaves.
        for dotted in adapter_module_paths(bank.stacks):
            mod = _get_path(bank.stacks, dotted)
            assert np.all(np.asarray(mod["a"][0]) == 0.0)
            assert np.all(np.asarray(mod["scale"])[0] == 0.0)

    def test_register_validates(self, tiny):
        _, _, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        ad = _nonzero_adapter(params, 4, seed=1)
        bank.register("a", ad)
        with pytest.raises(ValueError, match="already registered"):
            bank.register("a", ad)
        bank.register("a", ad, allow_update=True)
        with pytest.raises(ValueError, match="> bank rank"):
            bank.register("big", _nonzero_adapter(params, 8, seed=2))
        with pytest.raises(ValueError, match="non-empty string"):
            bank.register("", ad)
        with pytest.raises(UnknownAdapterError):
            bank.check_known("ghost")
        with pytest.raises(UnknownAdapterError):
            bank.unregister("ghost")

    def test_subset_target_adapter(self, tiny):
        """An adapter touching only q_proj shares the bank: its other
        modules are identity rows (zero delta)."""
        _, _, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        qa = init_lora_params(jax.random.PRNGKey(0), params,
                              LoRAConfig(rank=2, target_modules=("q_proj",)))
        bank.register("q-only", qa)
        row, hit, evicted = bank.acquire("q-only")
        assert (row, hit, evicted) == (1, False, None)
        bank.release("q-only")

    def test_lru_eviction_and_pins(self, tiny):
        _, _, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=2), max_adapters=3)
        for name in ("a", "b", "c"):
            bank.register(name, _nonzero_adapter(params, 2,
                                                 seed=ord(name)))
        ra, _, _ = bank.acquire("a")
        rb, _, _ = bank.acquire("b")
        assert {ra, rb} == {1, 2}
        bank.release("a")
        bank.release("b")
        # "a" is LRU: loading "c" evicts it, reusing its row.
        rc, hit, evicted = bank.acquire("c")
        assert (rc, hit, evicted) == (ra, False, "a")
        # "b" is still resident: re-acquire is a hit, no load.
        rb2, hit, evicted = bank.acquire("b")
        assert (rb2, hit, evicted) == (rb, True, None)
        # Both rows pinned: "a" cannot come back until someone releases.
        with pytest.raises(AdapterBankFull):
            bank.acquire("a")
        bank.release("b")
        ra2, _, evicted = bank.acquire("a")
        assert ra2 == rb and evicted == "b"
        c = bank.counters()
        assert c["loads"] == 4 and c["evictions"] == 2
        with pytest.raises(RuntimeError, match="in-flight"):
            bank.unregister("a")

    def test_row_write_loads_actual_bytes(self, tiny):
        _, _, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        ad = _nonzero_adapter(params, 4, seed=11)
        bank.register("x", ad)
        row, _, _ = bank.acquire("x")
        gathered = jax.tree_util.tree_map(lambda s: s[row], bank.stacks)
        padded = pad_adapter(ad, 4)
        for dotted in adapter_module_paths(padded):
            got = _get_path(gathered, dotted)
            want = _get_path(padded, dotted)
            assert np.array_equal(np.asarray(got["a"], np.float32),
                                  np.asarray(want["a"], np.float32))
            assert np.array_equal(np.asarray(got["b"], np.float32),
                                  np.asarray(want["b"], np.float32))


# ---------------------------------------------------------------------------
# served exactness: {rank 4, rank 8} x {greedy, sampled} x one shared batch
# ---------------------------------------------------------------------------
class TestServedExactness:
    """Base (slot-0 identity) + a rank-4 tenant + a rank-8 tenant share
    one decode batch; every stream must equal offline generate on that
    tenant's merged weights (rank mixing via zero-padding included)."""

    N = 10

    @pytest.fixture(scope="class")
    def setup(self, tiny):
        _, m, params = tiny
        ad4 = _nonzero_adapter(params, 4, seed=21)
        ad8 = _nonzero_adapter(params, 8, seed=22)

        def mk(do_sample):
            bank = AdapterBank(params, config=LoRAConfig(rank=8),
                               max_adapters=4)
            kw = dict(do_sample=True, temperature=0.9, top_k=50) \
                if do_sample else {}
            eng = ServingEngine(m, params, max_slots=3, max_len=64,
                                eos_token_id=EOS, adapters=bank, **kw)
            eng.register_adapter("r4", ad4)
            eng.register_adapter("r8", ad8)
            return eng

        engines = {"greedy": mk(False), "sampled": mk(True)}
        refs = {"r4": merge_adapter(params, ad4),
                "r8": merge_adapter(params, ad8),
                None: params}
        yield m, engines, refs
        for e in engines.values():
            if e.running:
                e.shutdown(drain=False)

    @pytest.mark.parametrize("mode", ["greedy", "sampled"])
    def test_mixed_batch_matches_merged_offline(self, setup, mode):
        m, engines, refs = setup
        eng = engines[mode]
        prompt = np.array([[3, 5, 2, 9, 11]], np.int32)
        reqs = {}
        for i, name in enumerate([None, "r4", "r8"]):
            seed = None if mode == "greedy" else 50 + i
            reqs[name] = eng.submit(prompt, max_new_tokens=self.N,
                                    seed=seed, adapter=name)
            time.sleep(0.01)  # staggered: tenants join a live batch
        kw = dict(do_sample=True, temperature=0.9, top_k=50) \
            if mode == "sampled" else {}
        outs = {}
        for i, (name, r) in enumerate(reqs.items()):
            seed = None if mode == "greedy" else 50 + i
            ref = _offline(m, refs[name], prompt, self.N, seed=seed, **kw)
            got = r.result(timeout=120)
            _assert_matches_offline(got, ref, self.N)
            outs[name] = np.asarray(got)
        # The tenants are real tenants: their streams differ.
        assert not np.array_equal(outs["r4"], outs["r8"])

    def test_base_identical_to_bankless_engine(self, setup, tiny):
        """Slot 0's identity delta is exactly 0.0: base requests through
        the bank engine are bit-identical to a bank-less engine."""
        _, m, params = tiny
        m2, engines, _ = setup
        prompt = np.array([[8, 6, 4, 2, 10]], np.int32)
        bankless = ServingEngine(m, params, max_slots=2, max_len=64,
                                 eos_token_id=EOS)
        try:
            a = engines["greedy"].submit(
                prompt, max_new_tokens=self.N).result(timeout=120)
            b = bankless.submit(
                prompt, max_new_tokens=self.N).result(timeout=120)
        finally:
            bankless.shutdown(drain=False)
        assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_unknown_adapter_rejected_at_submit(self, setup):
        _, engines, _ = setup
        with pytest.raises(UnknownAdapterError):
            engines["greedy"].submit(np.array([[1, 2]], np.int32),
                                     max_new_tokens=2, adapter="ghost")

    def test_adapter_requires_bank(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, warmup=False)
        try:
            with pytest.raises(ValueError, match="AdapterBank"):
                eng.submit(np.array([[1, 2]], np.int32),
                           max_new_tokens=2, adapter="x")
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# zero recompiles across hot-load / evict
# ---------------------------------------------------------------------------
class TestZeroRecompileAdapters:
    def test_load_evict_mid_serve_compiles_nothing(self, tiny):
        """The tentpole's acceptance bar: after warmup, registering a NEW
        adapter, loading it, and evicting an old one mid-serve triggers
        zero compile/trace events; the steady state stays one executable
        each for prefill_chunk, restore_prefix, and decode (the bank row
        write was compiled at bank construction)."""
        _, m, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=16,
                            prefix_cache_mb=4.0, adapters=bank)
        eng.register_adapter("a", _nonzero_adapter(params, 4, seed=31))
        eng.register_adapter("b", _nonzero_adapter(params, 4, seed=32))
        prompt = np.array([[3, 5, 2, 9]], np.int32)
        try:
            with CompileWatcher() as watcher:
                # Fill both rows, then hot-register "c" and serve it — its
                # load must evict the LRU resident with zero compiles.
                for name in ("a", "b"):
                    eng.submit(prompt, max_new_tokens=4,
                               adapter=name).result(timeout=120)
                eng.register_adapter("c", _nonzero_adapter(params, 4,
                                                           seed=33))
                for name in ("c", "a", None, "b"):
                    eng.submit(prompt, max_new_tokens=4,
                               adapter=name).result(timeout=120)
        finally:
            counters = bank.counters()
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — adapter "
            "membership must be data (bank rows), never program shapes")
        assert eng._prefill_chunk._cache_size() == 1
        # Paged + private alias cache restores by host page-table writes —
        # no compiled restore program exists to pin.
        if eng._restore_prefix is not None:
            assert eng._restore_prefix._cache_size() == 1
        assert eng._decode._cache_size() == 1
        assert counters["evictions"] >= 1  # the churn actually happened


# ---------------------------------------------------------------------------
# prefix-cache tenant isolation
# ---------------------------------------------------------------------------
class TestPrefixCacheTenantIsolation:
    def test_warm_prefix_does_not_cross_tenants(self, tiny):
        """Regression: before adapter-aware keying, tenant B would HIT
        tenant A's cached prefix KV and decode from A's activations. The
        same prompt must be a cache miss under a different adapter (and
        under base), while a repeat under the SAME adapter hits — with
        every stream still matching its own merged-offline reference."""
        _, m, params = tiny
        ad_a = _nonzero_adapter(params, 4, seed=41)
        ad_b = _nonzero_adapter(params, 4, seed=42)
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        eng = ServingEngine(m, params, max_slots=2, max_len=96,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=8.0, adapters=bank)
        eng.register_adapter("A", ad_a)
        eng.register_adapter("B", ad_b)
        prompt = np.arange(1, 25, dtype=np.int32)[None, :]  # 3 full chunks
        n = 6
        refs = {"A": merge_adapter(params, ad_a),
                "B": merge_adapter(params, ad_b), None: params}

        def hits():
            return eng.serving_metrics()["prefix_cache_hit_chunks"]

        def run(adapter):
            before = hits()
            r = eng.submit(prompt, max_new_tokens=n, adapter=adapter)
            got = r.result(timeout=120)
            _assert_matches_offline(got, _offline(m, refs[adapter], prompt, n),
                                    n)
            return hits() - before

        try:
            assert run("A") == 0        # cold
            assert run("B") == 0        # MISS: A's KV must not leak to B
            assert run(None) == 0       # MISS: nor to base
            assert run("A") > 0         # same tenant: warm
            assert run("B") > 0
            assert run(None) > 0
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# bank-full admission behavior
# ---------------------------------------------------------------------------
class TestBankPressure:
    def test_bank_full_fails_request_not_engine(self, tiny):
        """With every row pinned by in-flight streams, a new tenant's
        request FAILS with AdapterBankFull while the engine stays healthy
        and the pinned streams finish normally."""
        import bench

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        m = bench._sleepy_llama_cls(step_ms=10.0)(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=1,
                               seq_len=8)
        bank = AdapterBank(params, config=LoRAConfig(rank=2), max_adapters=2)
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            adapters=bank)
        eng.register_adapter("a", _nonzero_adapter(params, 2, seed=51))
        eng.register_adapter("b", _nonzero_adapter(params, 2, seed=52))
        prompt = np.array([[3, 5, 2]], np.int32)
        try:
            long = eng.submit(prompt, max_new_tokens=24, adapter="a",
                              ignore_eos=True)
            deadline = time.monotonic() + 60
            while not long.tokens and time.monotonic() < deadline:
                time.sleep(0.005)
            assert long.tokens, "long stream never started"
            # Row 1 (the only non-identity row) is pinned by "a".
            blocked = eng.submit(prompt, max_new_tokens=4, adapter="b")
            blocked.wait(timeout=60)
            assert blocked.status.value == "failed"
            assert isinstance(blocked.error, AdapterBankFull)
            assert eng.healthy and eng.error is None
            long.result(timeout=120)  # pinned stream unharmed
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# per-adapter metrics
# ---------------------------------------------------------------------------
class TestAdapterMetrics:
    def test_per_adapter_counters_flow_to_summary(self, tiny):
        _, m, params = tiny
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=3)
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, adapters=bank)
        eng.register_adapter("x", _nonzero_adapter(params, 4, seed=61))
        prompt = np.array([[3, 5, 2, 9]], np.int32)
        try:
            for _ in range(2):
                eng.submit(prompt, max_new_tokens=4,
                           adapter="x", ignore_eos=True).result(timeout=120)
            s = eng.serving_metrics()
            assert s["adapter/x/requests"] == 2
            assert s["adapter/x/tokens"] == 8
            assert s["adapter/x/loads"] == 1
            assert s["adapter/x/hits"] == 1
            assert s["adapter_requests"] == 2
            assert s["adapters_tracked"] == 1
            per = eng.stats.per_adapter()
            assert per["x"]["requests"] == 2
            # summary() stays a flat scalar dict (tracking contract).
            assert all(np.isscalar(v) for v in s.values())
        finally:
            eng.shutdown(drain=False)


# ---------------------------------------------------------------------------
# soak (excluded from tier-1)
# ---------------------------------------------------------------------------
@pytest.mark.slow
class TestAdapterSoak:
    def test_many_tenants_with_eviction_churn(self, tiny):
        """30 requests over 6 tenants through a capacity-3 bank: constant
        load/evict churn, every stream exact against its merged-offline
        reference, zero engine faults."""
        _, m, params = tiny
        n_tenants, n_requests, n_new = 6, 30, 6
        ads = {f"t{i}": _nonzero_adapter(params, 4, seed=70 + i)
               for i in range(n_tenants)}
        refs = {name: merge_adapter(params, ad) for name, ad in ads.items()}
        bank = AdapterBank(params, config=LoRAConfig(rank=4), max_adapters=4)
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, adapters=bank)
        for name, ad in ads.items():
            eng.register_adapter(name, ad)
        rng = np.random.default_rng(0)
        try:
            pending = []
            for i in range(n_requests):
                name = f"t{rng.integers(0, n_tenants)}"
                prompt = rng.integers(1, 200, size=(1, 5)).astype(np.int32)
                pending.append((name, prompt,
                                eng.submit(prompt, max_new_tokens=n_new,
                                           adapter=name, block=True)))
            for name, prompt, r in pending:
                _assert_matches_offline(
                    r.result(timeout=300),
                    _offline(m, refs[name], prompt, n_new), n_new)
            counters = bank.counters()
            assert counters["evictions"] > 0
            assert eng.healthy
        finally:
            eng.shutdown(drain=False)
