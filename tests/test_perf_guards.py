"""Tunnel-independent structural guards on the tier-1 fused train step.

The headline TPU benchmark (bench.py) divides measured throughput by an
ANALYTIC FLOPs count to report MFU, and its viability over a flaky tunnel
depends on structural properties of the lowered step (scan over layers, no
host traffic, donated state buffers, remat actually shrinking live memory).
These tests pin all of that on CPU via ``lower().compile()`` introspection,
so a regression is caught in CI instead of burning a rare tunnel window
(VERDICT r3 item 3).

Reference counterpart: the reference ships measured-hardware benchmarks
(`/root/reference/benchmarks/big_model_inference/README.md:26-37`) but has
no static FLOPs/memory guard; this lane is what makes the TPU-side MFU
denominator trustworthy without hardware in the loop.
"""

import dataclasses
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from accelerate_tpu import Accelerator, Model  # noqa: E402
from accelerate_tpu.data_loader import make_global_batch  # noqa: E402
from accelerate_tpu.models.llama import (  # noqa: E402
    LlamaConfig,
    LlamaForCausalLM,
    PipelinedLlamaForCausalLM,
    fused_causal_lm_loss,
)

BATCH, SEQ = 4, 256


def _tier1_like_config(remat=False, remat_policy="nothing"):
    """Scaled-down tier-1 shape (bench.py run_bench): same module classes,
    same loss, same step builder — only the dims shrink."""
    return LlamaConfig(
        vocab_size=512, hidden_size=128, intermediate_size=384,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=512, remat=remat, remat_policy=remat_policy,
        use_flash_attention=False,
    )


_compiled_cache = {}


def _compiled_step(remat=False, remat_policy="nothing"):
    """(compiled step, params, cfg) for the scaled tier-1 step; cached —
    each compile is several CPU-seconds."""
    key = (remat, remat_policy)
    if key in _compiled_cache:
        return _compiled_cache[key]
    cfg = _tier1_like_config(remat, remat_policy)
    model_def = PipelinedLlamaForCausalLM(cfg)
    params = model_def.init_params(jax.random.PRNGKey(0))
    acc = Accelerator(mixed_precision="bf16")
    model, opt = acc.prepare(Model(model_def, params), optax.adamw(1e-4))
    step = acc.compile_train_step(fused_causal_lm_loss(model_def), max_grad_norm=1.0)
    rng = np.random.default_rng(0)
    batch = make_global_batch(
        {"input_ids": rng.integers(0, cfg.vocab_size, size=(BATCH, SEQ)).astype(np.int32)},
        acc.mesh,
    )
    lowered = step._jitted.lower(
        model.params, opt.opt_state, opt.loss_scale, batch, jax.random.PRNGKey(0)
    )
    # Compile around the persistent cache (conftest warms one across runs):
    # a deserialized executable reports alias_size_in_bytes == 0, which
    # would fake a donation regression on any warm-cache run. jax latches
    # its cache-used decision at the first compile of the process, so the
    # config toggle only takes effect after reset_cache() drops the latch.
    from jax._src import compilation_cache as _cc

    cache_enabled = jax.config.jax_enable_compilation_cache
    try:
        jax.config.update("jax_enable_compilation_cache", False)
        _cc.reset_cache()
        compiled = lowered.compile()
    finally:
        jax.config.update("jax_enable_compilation_cache", cache_enabled)
        _cc.reset_cache()  # re-latch with the cache enabled for later tests
    _compiled_cache[key] = (compiled, model.params, cfg)
    return _compiled_cache[key]


def _flops(compiled) -> float:
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    return float(ca["flops"])


def _analytic_flops(cfg, params, layers=None) -> float:
    """bench.py's MFU denominator at (BATCH, SEQ) tokens; ``layers``
    overrides the layer count (for the scan-counted-once bound)."""
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
    n_matmul = n_params - cfg.vocab_size * cfg.hidden_size
    if layers is not None:
        per_layer = (
            2 * cfg.hidden_size * cfg.hidden_size                      # q, o proj
            + 2 * cfg.hidden_size * (cfg.num_key_value_heads
                                     * cfg.hidden_size // cfg.num_attention_heads)
            + 3 * cfg.hidden_size * cfg.intermediate_size              # mlp
        )
        n_matmul -= (cfg.num_hidden_layers - layers) * per_layer
        cfg_layers = layers
    else:
        cfg_layers = cfg.num_hidden_layers
    attn = 12.0 * cfg_layers * cfg.hidden_size * SEQ
    return (6.0 * n_matmul + attn) * BATCH * SEQ


class TestMFUDenominator:
    def test_analytic_formula_matches_xla_on_unrolled_model(self):
        """model_flops_per_token (6N + attention term) IS the MFU
        denominator; on the unrolled model XLA's own cost analysis must
        agree to a few percent — the analytic count a slight lower bound
        (XLA adds softmax/norm/rotary elementwise work)."""
        cfg = dataclasses.replace(_tier1_like_config(), num_hidden_layers=2)
        model = LlamaForCausalLM(cfg)
        params = model.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        ids = jnp.zeros((BATCH, SEQ), jnp.int32)

        def loss(p, ids):
            logits = model.apply({"params": p}, ids)
            tgt = jnp.roll(ids, -1, axis=1)
            lo = jax.nn.log_softmax(logits.astype(jnp.float32))
            return -jnp.take_along_axis(lo, tgt[..., None], -1).mean()

        compiled = jax.jit(jax.grad(loss)).lower(params, ids).compile()
        xla = _flops(compiled)
        n_params = sum(int(np.prod(p.shape)) for p in jax.tree_util.tree_leaves(params))
        n_matmul = n_params - cfg.vocab_size * cfg.hidden_size
        analytic = bench.model_flops_per_token(n_matmul, cfg, SEQ) * BATCH * SEQ
        ratio = xla / analytic
        assert 1.0 <= ratio <= 1.05, (
            f"XLA/analytic FLOPs ratio {ratio:.4f} out of band — the MFU "
            "denominator (bench.model_flops_per_token) no longer describes "
            "what the compiled step executes")

    def test_scanned_step_keeps_layer_scan(self):
        """XLA's cost model counts a lax.scan body ONCE; the fused tier-1
        step must therefore report far fewer FLOPs than the full analytic
        count (scan present) but at least the single-layer count (body not
        degenerate). An accidental unroll (or a cost-model change that
        starts multiplying by trip count) breaks the upper bound loudly."""
        compiled, params, cfg = _compiled_step()
        xla = _flops(compiled)
        full = _analytic_flops(cfg, params)
        single = _analytic_flops(cfg, params, layers=1)
        assert xla < 0.6 * full, (
            f"step reports {xla:.3e} FLOPs >= 60% of the analytic full count "
            f"{full:.3e}: either the layer scan unrolled (compile-time blowup "
            "over the tunnel) or XLA began counting scan trips — re-derive "
            "the MFU accounting either way")
        assert xla > 0.5 * single, (
            f"step reports {xla:.3e} FLOPs < half the single-layer analytic "
            f"count {single:.3e}: the loss/grad graph lost real work")


class TestInputPipelineOverlap:
    """CPU guards for the async host input pipeline (bench.overlap_microbench):
    a slow producer + a jitted step must OVERLAP — wall-clock near
    max(producer, step), not their sum — and a fast producer must leave the
    step loop essentially never waiting on data. 8 ms legs keep scheduler
    jitter small relative to the thresholds on loaded CI machines, and each
    guard retries once: the thresholds come from real sleeps, so a single
    burst of scheduler/GIL contention on an oversubscribed runner must not
    fail the suite — only a *reproducible* miss does."""

    PRODUCE_MS = 8.0
    STEP_MS = 8.0
    STEPS = 30

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_async_pipeline_overlaps_producer_and_step(self):
        def attempt():
            on = bench.overlap_microbench(
                steps=self.STEPS, produce_ms=self.PRODUCE_MS, step_ms=self.STEP_MS,
                async_prefetch=True)
            off = bench.overlap_microbench(
                steps=self.STEPS, produce_ms=self.PRODUCE_MS, step_ms=self.STEP_MS,
                async_prefetch=False)
            assert on["wall_s"] < 1.5 * on["ideal_s"], (
                f"async pipeline took {on['wall_s']:.3f}s >= 1.5x the ideal "
                f"max(producer, step) {on['ideal_s']:.3f}s: input work is not "
                "overlapping the step")
            speedup = off["wall_s"] / on["wall_s"]
            assert speedup >= 1.4, (
                f"async speedup vs async_prefetch=False only {speedup:.2f}x "
                f"(async {on['wall_s']:.3f}s, sync {off['wall_s']:.3f}s): the "
                "background worker is no longer hiding producer latency")
            # The sync loop must *measure* its serialized data wait — that
            # metric is how a production run discovers it needs the async path.
            assert off["data_wait_ms"] > 0.5 * self.PRODUCE_MS

        self._retry_once(attempt)

    def test_fast_producer_near_zero_data_wait(self):
        def attempt():
            out = bench.overlap_microbench(
                steps=self.STEPS, produce_ms=0.0, step_ms=5.0, async_prefetch=True)
            assert out["data_wait_ms"] < 2.0, (
                f"mean data_wait_ms {out['data_wait_ms']:.3f} with an instant "
                "producer: the prefetch queue is not staying ahead of the step")
            assert out["batches_waited"] == self.STEPS

        self._retry_once(attempt)


class TestFusedStepStructure:
    def test_no_host_memory_in_step(self):
        """The non-offload step must stay device-resident end to end: any
        host buffer in the executable means a hidden transfer inside the
        hot loop (HBM <-> host is the tunnel's slowest edge)."""
        compiled, _, _ = _compiled_step()
        mem = compiled.memory_analysis()
        host = (mem.host_argument_size_in_bytes + mem.host_output_size_in_bytes
                + mem.host_temp_size_in_bytes)
        assert host == 0, f"step holds {host} host bytes"

    def test_donation_aliases_params_and_opt_state(self):
        """donate_argnums must alias params + optimizer state into the
        outputs — losing donation doubles the step's parameter footprint."""
        compiled, params, _ = _compiled_step()
        mem = compiled.memory_analysis()
        param_bytes = sum(
            int(np.prod(p.shape)) * p.dtype.itemsize
            for p in jax.tree_util.tree_leaves(params))
        opt_bytes = 2 * param_bytes  # adamw m + v, fp32 like the params
        assert mem.alias_size_in_bytes >= 0.95 * (param_bytes + opt_bytes), (
            f"aliased {mem.alias_size_in_bytes} < params+opt "
            f"{param_bytes + opt_bytes}: buffer donation regressed")

    def test_remat_shrinks_live_memory(self):
        """cfg.remat must visibly trade FLOPs for memory in the scanned
        model (guards the per-layer-checkpoint placement inside the scan
        body — checkpointing the whole scan saves nothing at peak), and
        the 'dots' policy must sit between 'nothing' and no-remat."""
        base, _, _ = _compiled_step(remat=False)
        full_remat, _, _ = _compiled_step(remat=True, remat_policy="nothing")
        dots, _, _ = _compiled_step(remat=True, remat_policy="dots")
        t_base = base.memory_analysis().temp_size_in_bytes
        t_full = full_remat.memory_analysis().temp_size_in_bytes
        t_dots = dots.memory_analysis().temp_size_in_bytes
        assert t_full < 0.5 * t_base, (
            f"remat temp {t_full} not < 50% of no-remat {t_base}: "
            "rematerialization is not reaching the scan body")
        assert t_full <= t_dots <= t_base, (t_full, t_dots, t_base)


class TestContinuousBatching:
    """CPU guard for the serving engine's scheduling win
    (bench.continuous_vs_static): with deterministic per-forward sleeps
    standing in for device step time, short staggered requests stuck
    behind one long request must finish ~Nx faster under continuous
    batching (slot joins mid-flight) than under static dynamic batching
    (head-of-line blocking until the whole batch drains). Sleep-driven
    like the overlap guards above, and retried once for the same reason:
    only a reproducible miss fails the suite."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_continuous_beats_static_on_staggered_arrivals(self):
        def attempt():
            out = bench.continuous_vs_static()
            assert out["speedup"] >= 1.5, (
                f"continuous batching speedup on short requests only "
                f"{out['speedup']:.2f}x (static {out['static_short_latency_s']:.3f} s "
                f"vs continuous {out['continuous_short_latency_s']:.3f} s): slot "
                "admission is no longer overlapping the long request's decode")
            # The win must come from scheduling, not from dropping work:
            st = out["continuous_stats"]
            assert st["requests_completed"] == out["n_short"] + 1

        self._retry_once(attempt)


class TestChunkedPrefill:
    """CPU guards for bounded-latency admission
    (bench.chunked_prefill_interference / prefix_cache_hit_bench): on the
    per-token deterministic-sleep model, a long prompt arriving over
    active decode streams must neither stall their next token for its
    whole prefill nor push late short arrivals' TTFT behind it — chunked
    admission interleaves chunk calls with decode ticks. Sleep-driven and
    retried once, same as the guards above. The prefix-cache guard is
    counter-exact (no timing), so it runs once."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_chunked_admission_bounds_interference(self):
        def attempt():
            out = bench.chunked_prefill_interference()
            assert out["ttft_speedup"] >= 2.0, (
                f"late short arrivals' TTFT p95 only {out['ttft_speedup']:.2f}x "
                f"better chunked (chunked {out['chunked']['late_ttft_ms_p95']:.0f} ms "
                f"vs monolithic {out['monolithic']['late_ttft_ms_p95']:.0f} ms): "
                "admission is no longer interleaving chunk calls with decode "
                "ticks and new arrivals")
            # The decode stall bound is the tentpole claim: the worst
            # tick-to-tick gap under chunked admission must stay a small
            # multiple of one chunk, far below the monolithic whole-prefill
            # stall.
            assert out["itl_stall_speedup"] >= 4.0, (
                f"worst stream inter-token gap only {out['itl_stall_speedup']:.2f}x "
                f"better chunked ({out['chunked']['stream_itl_ms_max']:.0f} ms vs "
                f"{out['monolithic']['stream_itl_ms_max']:.0f} ms): chunk calls "
                "are no longer bounding the admission stall")
            # The win must come from scheduling, not from skipping prefill:
            assert out["chunked"]["prefill_chunks"] == (
                -(-out["long_prompt_len"] // out["prefill_chunk"])
                + out["n_late"])

        self._retry_once(attempt)

    def test_cached_prefix_admits_in_one_chunk(self):
        out = bench.prefix_cache_hit_bench()
        assert out["warm_prefill_chunks"] == 1, (
            f"repeat of an identical {out['chunks_per_prompt']}-chunk prompt "
            f"cost {out['warm_prefill_chunks']} chunk calls — the prefix "
            "cache must reduce admission to the final chunk only")
        assert out["hit_chunks"] == out["chunks_per_prompt"] - 1
        assert out["cold_prefill_chunks"] == out["chunks_per_prompt"]
        assert out["tokens_equal"], (
            "restored-prefix decode diverged from the cold run")
        assert out["restored_bytes"] > 0 and out["cache_entries"] >= 1


class TestGatewayOverhead:
    """CPU guard for the HTTP serving layer (bench.gateway_overhead_bench):
    on the deterministic-sleep model, p95 TTFT through the full gateway
    stack (HTTP parse -> router -> engine -> SSE first event) must stay
    within 2x of direct ``engine.submit`` on the same warmed engine — the
    acceptance bound on what the network front door may cost. Sleep-driven
    and retried once, same as the other timing guards."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    @pytest.mark.slow
    def test_gateway_ttft_within_2x_of_direct_submit(self):
        def attempt():
            out = bench.gateway_overhead_bench()
            assert out["overhead_ratio_p95"] is not None
            assert out["overhead_ratio_p95"] <= 2.0, (
                f"gateway p95 TTFT {out['http_ttft_ms_p95']:.1f} ms is "
                f"{out['overhead_ratio_p95']:.2f}x direct submit "
                f"({out['direct_ttft_ms_p95']:.1f} ms): the HTTP layer is "
                "adding more than routing + serialization")

        self._retry_once(attempt)


class TestAsyncioGateway:
    """Open-loop A/B guard for the asyncio front end
    (bench.open_loop_ab_bench): identical heavy-tailed open-loop load
    against both front ends, with the threading gateway capped at a
    small connection count so the burst pushes it past its knee. Past
    that knee the threading side refuses/queues at the front door (its
    p99 TTFT from scheduled arrival goes unbounded and is clamped at
    the wall deadline) while the asyncio side keeps every stream open —
    the acceptance bound is a >=2x p99-TTFT advantage. Timing-driven
    and retried once, same as the other guards."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    @pytest.mark.slow
    def test_asyncio_p99_ttft_2x_better_past_threading_knee(self):
        def attempt():
            out = bench.open_loop_ab_bench()
            assert out["threading_conn_rejections"] > 0, (
                "the A/B load never hit the threading connection cap — "
                "the comparison stayed in the flat region and proves "
                "nothing")
            ratio = out["p99_ttft_ratio_threading_over_asyncio"]
            assert ratio is not None and ratio >= 2.0, (
                f"asyncio p99 TTFT advantage past the threading knee is "
                f"only {ratio}x (threading "
                f"{out['threading']['ttft_s']['p99_clamped']}s vs asyncio "
                f"{out['asyncio']['ttft_s']['p99_clamped']}s): the "
                "event-loop front end is no longer absorbing the burst "
                "the thread-per-connection front end refuses")

        self._retry_once(attempt)


class TestSLOControl:
    """Open-loop A/B guard for the SLO control plane
    (bench.slo_control_bench): the same seeded mixed interactive/batch
    load at ~2x saturation against an FCFS fleet
    (``priority_policy=None``) and the default priority-policy fleet.
    With a deep admission queue the control plane's priority admission
    must cut the interactive class's clamped p99 TTFT by >=2x versus
    FCFS — the headline SLO claim — without starving batch (every
    stream still completes; the policy reorders, it does not drop).
    Timing-driven and retried once, same as the other guards."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    @pytest.mark.slow
    def test_interactive_p99_ttft_2x_better_than_fcfs(self):
        def attempt():
            out = bench.slo_control_bench()
            ratio = out["interactive_p99_ttft_ratio_fcfs_over_control"]
            assert ratio is not None and ratio >= 2.0, (
                f"interactive p99-TTFT advantage of the priority policy "
                f"over FCFS at 2x saturation is only {ratio}x "
                f"(FCFS {out['fcfs']['per_priority']['interactive']['ttft_s']['p99_clamped']}s "
                f"vs control "
                f"{out['control']['per_priority']['interactive']['ttft_s']['p99_clamped']}s): "
                "interactive arrivals are no longer jumping the batch "
                "backlog")
            assert (out["batch_completed_under_control"] or 0) > 0, (
                "priority scheduling starved the batch class outright")
            assert out["control"]["counters_balance"], (
                "control-plane run lost or duplicated stream outcomes")

        self._retry_once(attempt)


class TestObservabilityOverhead:
    """CPU guard for always-on tracing (bench.tracing_overhead_bench): with
    the span tracer enabled the engine must keep >=95% of its untraced
    decode throughput on identical traffic — the acceptance budget that
    lets tracing default ON in production. The tracer is host-side tuple
    appends into per-thread rings; if this ratio regresses, someone put
    work (or a lock) on the decode hot path. Timing-driven and retried
    once, same as the other guards."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_tracing_keeps_95_percent_decode_throughput(self):
        def attempt():
            out = bench.tracing_overhead_bench()
            assert out["overhead_ratio"] >= 0.95, (
                f"tracing-on decode throughput is only "
                f"{out['overhead_ratio']:.3f}x of tracing-off "
                f"({out['tracing_on']['decode_tokens_per_sec']:.0f} vs "
                f"{out['tracing_off']['decode_tokens_per_sec']:.0f} tok/s): "
                "the span tracer is adding hot-path cost beyond ring appends")
            # the traced arm must actually have traced something, and the
            # untraced arm must be a true zero-overhead no-op
            assert out["tracing_on"]["spans_buffered"] > 0
            assert out["tracing_off"]["spans_buffered"] == 0

        self._retry_once(attempt)


class TestMultiTenantAdapters:
    """CPU guard for the adapter bank's serving win
    (bench.multi_tenant_adapter_bench): at 4 tenants, batching per-slot
    low-rank deltas through one shared compiled program must beat the
    sequential merge-swap-generate baseline by >= 2x, while remaining
    token-identical to generating on each tenant's merged weights.
    Sleep-driven like the guards above, retried once so only a
    reproducible miss fails the suite."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_bank_beats_sequential_merge_swap(self):
        def attempt():
            out = bench.multi_tenant_adapter_bench()
            assert out["tokens_equal"], (
                "batched adapter decode diverged from per-tenant merged "
                "weights — the bank gather is no longer exact")
            assert out["speedup"] >= 2.0, (
                f"multi-tenant speedup only {out['speedup']:.2f}x "
                f"(sequential swap {out['sequential_swap_s']:.3f} s vs "
                f"batched {out['batched_s']:.3f} s): adapter requests are "
                "no longer sharing decode ticks across tenants")
            assert out["adapter_requests"] == out["n_tenants"]

        self._retry_once(attempt)


class TestPagedCapacity:
    """CPU guard for the paged KV pool's capacity win
    (bench.paged_capacity_bench): at equal KV HBM the paged engine must
    sustain >= 2x the dense engine's peak concurrency on short traffic
    (the benchmark geometry gives 4x: a 16-token request covers 2 of the
    pool's 16 pages where dense reserves a whole 64-token row), with
    greedy output token-identical and zero pool-exhaustion preemptions —
    the advertised concurrency really fits. Sleep-driven, retried once so
    only a reproducible miss fails the suite."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_paged_serves_2x_slots_at_equal_hbm(self):
        def attempt():
            out = bench.paged_capacity_bench()
            assert out["tokens_equal"], (
                "paged greedy output diverged from dense — the page "
                "gather/scatter is no longer an exact relayout")
            ratio = out["slots_ratio"]
            assert ratio >= 2.0, (
                f"paged peak concurrency only {ratio:.2f}x dense "
                f"({out['peak_concurrency']}) at equal KV HBM "
                f"({out['kv_bytes']}): the pool is no longer translating "
                "short requests into extra live slots")
            assert out["preemptions"] == 0, (
                f"{out['preemptions']} preemptions at the advertised "
                "concurrency — the pool does not actually fit it")

        self._retry_once(attempt)


class TestQuantizedServing:
    """CPU guard for int8 KV serving (bench.quantized_serving_bench):
    at equal pool BYTES the int8 engine (quantized pages + per-page
    scales) must sustain >= 1.8x the fp engine's peak concurrency (the
    template geometry gives 2x: 1040-byte int8 pages vs 2048-byte fp
    pages buy 31 pages for the fp pool's 16), with zero preemptions,
    int8-kv greedy output in near-total agreement with fp, and
    ``logprob_drift`` (teacher-forced fp-vs-quantized-weights max
    |delta logprob| on served tokens) under the documented 0.25
    tolerance. Speculation accept rate must not collapse under
    quantized pages. Sleep-driven, retried once so only a reproducible
    miss fails the suite."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_int8_kv_buys_concurrency_at_equal_hbm(self):
        def attempt():
            out = bench.quantized_serving_bench()
            assert out["kv_bytes"]["int8"] <= out["kv_bytes"]["fp"], (
                f"int8 pool is not within the fp byte budget "
                f"({out['kv_bytes']}): the A/B is no longer equal-HBM")
            ratio = out["concurrency_ratio"]
            assert ratio >= 1.8, (
                f"int8 peak concurrency only {ratio:.2f}x fp "
                f"({out['peak_concurrency']}) at equal pool bytes "
                f"({out['kv_bytes']}): quantized pages are no longer "
                "translating the byte savings into live slots")
            assert out["preemptions"] == 0, (
                f"{out['preemptions']} preemptions at the advertised "
                "int8 concurrency — the quantized pool does not fit it")
            assert out["token_agreement"]["kv"] >= 0.9, (
                f"int8-kv greedy agreement {out['token_agreement']} vs "
                "fp collapsed — per-page scales are mangling the "
                "dequantized attention view, not just rounding it")
            assert out["logprob_drift"] <= 0.25, (
                f"logprob_drift {out['logprob_drift']} above the "
                "documented 0.25 tolerance — weight quantization is no "
                "longer bounded-divergence")
            assert (out["spec_accept_rate"]["int8"]
                    >= out["spec_accept_rate"]["fp"] - 0.1), (
                f"speculation accept rate collapsed under int8 pages "
                f"({out['spec_accept_rate']}): draft and target no "
                "longer see the same dequantized view")

        self._retry_once(attempt)


class TestSpeculativeDecoding:
    """CPU guard for universal speculative decoding
    (bench.speculative_bench): on the deterministic biased-logits
    fixture the verify step must accept > 1.3 committed tokens per tick
    (1.0 = speculation never helps) while staying token-identical to the
    non-speculative twin — in the greedy base case AND in every
    previously-rejected mode (sampled, adapter tenant, tp=2 slice,
    draft-free prompt lookup). A drop below the bar means the
    draft/verify chains stopped agreeing (cache corruption, position
    skew, rng drift), not a model change — the fixture has no ties to
    flake on. Retried once."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_accepted_tokens_per_step_all_modes(self):
        def attempt():
            out = bench.speculative_bench()
            cells = {"greedy": out}
            cells.update(out["modes"])
            for name, cell in cells.items():
                if "skipped" in cell:
                    continue
                assert cell["tokens_equal"], (
                    f"[{name}] speculative output diverged from its "
                    "non-speculative twin — the verify/commit chain "
                    "broke exactness")
                tps = cell["accepted_tokens_per_step"]
                assert tps > 1.3, (
                    f"[{name}] only {tps:.2f} committed tokens per "
                    f"speculative tick (ticks {cell['ticks']}): proposals "
                    "are no longer being accepted")
                assert (cell["ticks"]["speculative"]
                        < cell["ticks"]["baseline"]), name

        self._retry_once(attempt)


class TestAsyncHostRuntime:
    """CPU guard for the async host runtime (bench.host_overlap_bench):
    on the deterministic sleepy model (12 ms device leg) with a 4 ms
    ``on_token`` consumer per stream, the sync engine's ITL is additive
    (step + host schedule/commit + inline callbacks) while the async
    engine overlaps scheduling with the in-flight tick and drains
    callbacks off-thread — its ITL must stay within striking distance of
    the device leg, giving a >= 1.3x ITL win. A drop means one-tick-ahead
    dispatch stopped overlapping (a hidden sync point in dispatch) or
    emission moved back inline. Sleep-driven, so retried once: only a
    reproducible miss fails the suite."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def test_async_itl_beats_sync_by_1_3x(self):
        def attempt():
            out = bench.host_overlap_bench()
            a, s = out["async"], out["sync"]
            assert out["itl_ratio"] >= 1.3, (
                f"async-vs-sync ITL ratio only {out['itl_ratio']:.2f}x "
                f"(sync {s['itl_mean_ms']:.2f} ms, async "
                f"{a['itl_mean_ms']:.2f} ms at a {out['step_ms']} ms device "
                "leg): the host runtime is no longer hiding schedule/commit/"
                "emission time behind the in-flight tick")
            # The split metric must attribute the win: the async engine's
            # measured host time per tick has to be well under the sync
            # engine's (which bills the inline callbacks and the serialized
            # schedule+commit between device legs).
            assert a["host_us_per_tick"] < s["host_us_per_tick"], out

        self._retry_once(attempt)


class TestZeROShardedOptimizer:
    """CPU guards for ZeRO-1/2 optimizer-state sharding (arXiv:2004.13336,
    bench.zero_sharding_bench): the compiled dp=2 step must carry only
    ~1/dp of the optimizer-state bytes per replica as arguments, and the
    sharded update (reduce-scatter grads -> shard-local Adam -> all-gather
    params) must cost <= 1.2x the replicated step's wall time while
    tracking its loss trajectory to fp32-reassociation noise."""

    DP = 2

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    def _compiled_dp_step(self, zero):
        """(compiled executable, total opt-state bytes) for a dp=2 fused
        step over an MLP whose moments are dominated by shardable weights."""
        from accelerate_tpu import MeshConfig
        from accelerate_tpu.state import (AcceleratorState, GradientState,
                                          PartialState)

        for cls in (AcceleratorState, GradientState, PartialState):
            cls._reset_state()

        def apply(p, x):
            return jnp.tanh(x @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]

        def loss(p, batch):
            return jnp.mean((apply(p, batch["x"]) - batch["y"]) ** 2)

        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        params = {"w1": jax.random.normal(k1, (64, 512)) * 0.1,
                  "b1": jnp.zeros((512,)),
                  "w2": jax.random.normal(k2, (512, 64)) * 0.1,
                  "b2": jnp.zeros((64,))}
        acc = Accelerator(mesh_config=MeshConfig(
            dp=self.DP, devices=jax.devices()[:self.DP], zero_sharding=zero))
        model, opt = acc.prepare(Model(apply, params), optax.adamw(1e-3))
        step = acc.compile_train_step(loss, max_grad_norm=1.0)
        rng = np.random.default_rng(0)
        batch = make_global_batch(
            {"x": rng.normal(size=(16, 64)).astype(np.float32),
             "y": rng.normal(size=(16, 64)).astype(np.float32)}, acc.mesh)
        lowered = step._jitted.lower(model.params, opt.opt_state,
                                     opt.loss_scale, batch,
                                     jax.random.PRNGKey(0))
        from jax._src import compilation_cache as _cc

        cache_enabled = jax.config.jax_enable_compilation_cache
        try:
            jax.config.update("jax_enable_compilation_cache", False)
            _cc.reset_cache()
            compiled = lowered.compile()
        finally:
            jax.config.update("jax_enable_compilation_cache", cache_enabled)
            _cc.reset_cache()
        opt_bytes = sum(leaf.nbytes
                        for leaf in jax.tree_util.tree_leaves(opt.opt_state))
        return compiled, opt_bytes

    def test_per_replica_opt_state_args_near_1_over_dp(self):
        """memory_analysis guard: argument_size_in_bytes is PER DEVICE, and
        params/batch/scale/rng are byte-identical across the two compiles —
        so the replicated-vs-zero argument delta is exactly the optimizer
        state each replica no longer holds. The residue (what the zero step
        still carries) must be <= (1/dp + eps) of the replicated state; eps
        covers the deliberately replicated scalars and small biases."""
        compiled_r, opt_total = self._compiled_dp_step(zero=False)
        compiled_z, opt_total_z = self._compiled_dp_step(zero=True)
        assert opt_total == opt_total_z  # same tree, different placement
        arg_r = compiled_r.memory_analysis().argument_size_in_bytes
        arg_z = compiled_z.memory_analysis().argument_size_in_bytes
        per_replica_opt = opt_total - (arg_r - arg_z)
        bound = (1.0 / self.DP + 0.02) * opt_total
        assert per_replica_opt <= bound, (
            f"zero step still holds {per_replica_opt} opt-state bytes per "
            f"replica (> {bound:.0f} = (1/{self.DP}+eps) of {opt_total}): "
            "the moment shardings are not reaching the compiled step")

    def test_step_time_and_trajectory_within_budget(self):
        def attempt():
            out = bench.zero_sharding_bench(steps=15, warmup=3)
            assert not out.get("skipped"), out
            assert out["memory_ratio"] <= 1.0 / self.DP + 0.05, out
            ratio = out["step_time_ratio"]
            assert ratio <= 1.2, (
                f"zero-sharded step is {ratio:.2f}x the replicated step "
                f"({out['step_ms_zero']:.2f}ms vs "
                f"{out['step_ms_replicated']:.2f}ms): the reduce-scatter/"
                "all-gather lowering has become more than communication")
            assert out["max_loss_diff"] <= 1e-4, (
                f"loss diverged {out['max_loss_diff']} from the replicated "
                "trajectory — more than fp32 reduce-scatter reassociation")

        self._retry_once(attempt)


class TestChaosRecovery:
    """CPU guard for the self-healing loop (bench.chaos_recovery_bench):
    a scripted chaos kill at a fixed decode tick under a running
    FleetSupervisor must (a) finish every in-flight stream token-exact on
    the survivor within the recovery budget and (b) rebuild + re-warm the
    dead replica back to HEALTHY without operator action. Sleep-driven
    and retried once, same as the other timing guards."""

    @staticmethod
    def _retry_once(attempt):
        try:
            attempt()
        except AssertionError:
            attempt()

    @pytest.mark.slow
    def test_kill_recovery_and_rejoin_within_budget(self):
        def attempt():
            out = bench.chaos_recovery_bench()
            assert out["chaos_fired"] == ["kill"], out
            assert out["all_completed"] and out["tokens_exact"], (
                f"streams did not survive the chaos kill exactly: {out}")
            assert out["recovery_s"] <= 5.0, (
                f"kill -> all-streams-done took {out['recovery_s']:.2f}s "
                "on the sleepy model: failover is stalling, not retrying")
            assert out["rejoined_healthy"] and out["restarts"] >= 1, (
                f"supervisor never healed the killed replica: {out}")
            assert out["rejoin_s"] <= 60.0, (
                f"kill -> replica HEALTHY took {out['rejoin_s']:.2f}s: "
                "rebuild + three-executable warmup should be seconds "
                "on the tiny model")

        self._retry_once(attempt)
