"""Native host-IO runtime: parallel reads, prefetch ring, token loader."""

import os
import tempfile

import numpy as np
import pytest

from accelerate_tpu.native import PrefetchRing, available, parallel_read
from accelerate_tpu.native.io import TokenBinDataLoader, fast_load_safetensors


@pytest.fixture(scope="module")
def token_file():
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "tokens.bin")
        tokens = np.arange(10_000, dtype=np.int32)
        tokens.tofile(path)
        yield path, tokens


class TestParallelRead:
    def test_native_lib_builds(self):
        assert available(), "native lib should compile in this environment"

    def test_regions_round_trip(self, token_file):
        path, tokens = token_file
        # read 50 scattered 400-byte regions
        rng = np.random.default_rng(0)
        offsets = rng.integers(0, tokens.nbytes - 400, 50).astype(np.int64)
        sizes = np.full(50, 400, np.int64)
        dests = [np.empty(400, np.uint8) for _ in range(50)]
        parallel_read(path, offsets, sizes, dests, threads=8)
        raw = tokens.tobytes()
        for off, d in zip(offsets, dests):
            assert d.tobytes() == raw[off : off + 400]

    def test_validation(self, token_file):
        path, _ = token_file
        with pytest.raises(ValueError, match="equal length"):
            parallel_read(path, [0], [4, 8], [np.empty(8, np.uint8)])
        with pytest.raises(ValueError, match="smaller"):
            parallel_read(path, [0], [400], [np.empty(4, np.uint8)])

    def test_missing_file_raises(self):
        with pytest.raises(IOError):
            parallel_read("/nonexistent/file.bin", [0], [4], [np.empty(4, np.uint8)])


class TestPrefetchRing:
    def test_ordered_exact_batches(self, token_file):
        path, tokens = token_file
        sample_bytes = 16 * 4
        offsets = (np.arange(40, dtype=np.int64) * sample_bytes)
        ring = PrefetchRing(path, offsets, sample_bytes, batch_size=8, depth=3, threads=4)
        assert ring.num_batches == 5
        seen = []
        for buf, valid in ring:
            assert valid == 8
            seen.append(buf.view(np.int32).reshape(8, 16).copy())
        assert len(seen) == 5
        got = np.concatenate(seen).reshape(-1)
        np.testing.assert_array_equal(got, tokens[: 40 * 16])

    def test_shuffled_schedule_respected(self, token_file):
        path, tokens = token_file
        sample_bytes = 8 * 4
        order = np.array([5, 0, 3, 1], dtype=np.int64)
        ring = PrefetchRing(path, order * sample_bytes, sample_bytes, batch_size=2)
        batches = [buf.view(np.int32).reshape(2, 8)[:v].copy() for buf, v in ring]
        flat = np.concatenate(batches)
        for row, idx in zip(flat, order):
            np.testing.assert_array_equal(row, tokens[idx * 8 : idx * 8 + 8])

    def test_partial_final_batch(self, token_file):
        path, _ = token_file
        offsets = (np.arange(5, dtype=np.int64) * 32)
        ring = PrefetchRing(path, offsets, 32, batch_size=2)
        valids = [v for _, v in ring]
        assert valids == [2, 2, 1]

    def test_python_fallback_matches(self, token_file, monkeypatch):
        path, tokens = token_file
        sample_bytes = 8 * 4
        offsets = np.arange(6, dtype=np.int64) * sample_bytes
        ring = PrefetchRing(path, offsets, sample_bytes, batch_size=3)
        native = [(b.copy(), v) for b, v in ring]
        ring_py = PrefetchRing(path, offsets, sample_bytes, batch_size=3)
        ring_py._lib = None
        fallback = [(b.copy(), v) for b, v in ring_py._python_iter()]
        assert len(native) == len(fallback)
        for (a, va), (b, vb) in zip(native, fallback):
            assert va == vb
            np.testing.assert_array_equal(a[: va * sample_bytes], b[: va * sample_bytes])


class TestTokenBinDataLoader:
    def test_epoch_coverage_and_shapes(self, token_file):
        path, tokens = token_file
        dl = TokenBinDataLoader(path, seq_len=64, batch_size=4, shuffle=False)
        batches = list(dl)
        assert all(b["input_ids"].shape == (4, 64) for b in batches)
        got = np.concatenate([b["input_ids"] for b in batches]).reshape(-1)
        n = len(got)
        np.testing.assert_array_equal(got, tokens[:n])
        assert len(batches) == len(dl)

    def test_sharding_disjoint_and_complete(self, token_file):
        path, _ = token_file
        all_rows = []
        for rank in range(4):
            dl = TokenBinDataLoader(
                path, seq_len=32, batch_size=2, shuffle=True, seed=7,
                num_processes=4, process_index=rank,
            )
            all_rows += [tuple(r) for b in dl for r in b["input_ids"]]
        # disjoint across ranks
        assert len(all_rows) == len(set(all_rows))

    def test_shuffle_determinism_and_epoch_change(self, token_file):
        path, _ = token_file
        dl = TokenBinDataLoader(path, seq_len=32, batch_size=4, shuffle=True, seed=3)
        e0a = np.concatenate([b["input_ids"] for b in dl])
        dl2 = TokenBinDataLoader(path, seq_len=32, batch_size=4, shuffle=True, seed=3)
        e0b = np.concatenate([b["input_ids"] for b in dl2])
        np.testing.assert_array_equal(e0a, e0b)
        dl2.set_epoch(1)
        e1 = np.concatenate([b["input_ids"] for b in dl2])
        assert not np.array_equal(e0a, e1)

    def test_resume_skips_consumed_batches(self, token_file):
        path, _ = token_file
        dl = TokenBinDataLoader(path, seq_len=32, batch_size=4, shuffle=True, seed=5)
        it = iter(dl)
        consumed = [next(it)["input_ids"].copy() for _ in range(3)]
        state = dl.state_dict()
        rest_after_resume = []
        dl2 = TokenBinDataLoader(path, seq_len=32, batch_size=4, shuffle=True, seed=5)
        dl2.load_state_dict(state)
        rest_after_resume = [b["input_ids"].copy() for b in dl2]
        full = [b["input_ids"].copy() for b in TokenBinDataLoader(
            path, seq_len=32, batch_size=4, shuffle=True, seed=5)]
        np.testing.assert_array_equal(
            np.concatenate(rest_after_resume), np.concatenate(full[3:])
        )

    def test_feeds_train_step(self, token_file):
        import jax
        import optax

        from accelerate_tpu import Accelerator, MeshConfig, Model
        from accelerate_tpu.data_loader import make_global_batch
        from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM, causal_lm_loss

        path, _ = token_file
        cfg = LlamaConfig.tiny(vocab_size=16384, use_flash_attention=False)
        model_def = LlamaForCausalLM(cfg)
        params = model_def.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=32)
        acc = Accelerator(mesh_config=MeshConfig(dp=8))
        model, opt = acc.prepare(Model(model_def, params), optax.adamw(1e-3))
        step = acc.compile_train_step(causal_lm_loss(model_def.apply))
        dl = TokenBinDataLoader(path, seq_len=32, batch_size=8, shuffle=True)
        for i, batch in enumerate(dl):
            m = step(make_global_batch(batch, acc.mesh))
            if i >= 2:
                break
        assert np.isfinite(float(m["loss"]))


class TestFastSafetensors:
    def test_matches_safe_open(self):
        from safetensors.numpy import save_file
        from safetensors import safe_open

        rng = np.random.default_rng(0)
        tensors = {
            "a.weight": rng.normal(size=(128, 64)).astype(np.float32),
            "a.bias": rng.normal(size=(64,)).astype(np.float32),
            "b.weight": rng.integers(-100, 100, (32, 16)).astype(np.int32),
            "c.half": rng.normal(size=(8, 8)).astype(np.float16),
        }
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.safetensors")
            save_file(tensors, path)
            loaded = fast_load_safetensors(path, threads=4)
            assert set(loaded) == set(tensors)
            for k in tensors:
                np.testing.assert_array_equal(loaded[k], tensors[k])

    def test_bf16(self):
        import ml_dtypes
        from safetensors.numpy import save_file

        w = np.arange(64, dtype=np.float32).reshape(8, 8).astype(ml_dtypes.bfloat16)
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "m.safetensors")
            save_file({"w": w}, path)
            loaded = fast_load_safetensors(path)
            assert loaded["w"].dtype == ml_dtypes.bfloat16
            np.testing.assert_array_equal(loaded["w"], w)
