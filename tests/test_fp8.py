"""FP8 delayed-scaling training tests (ops/quant.py).

Parity target: the reference's TransformerEngine fp8 integration
(reference: src/accelerate/utils/transformer_engine.py:26-137, exercised by
tests/test_fp8.py there on H100 hardware). Here fp8 runs on every backend —
the fp8 dots are ordinary XLA ops — so the suite exercises the real path on
the CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from accelerate_tpu import Accelerator, MeshConfig, Model
from accelerate_tpu.data_loader import make_global_batch
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM, causal_lm_loss
from accelerate_tpu.ops.quant import (
    FP8_META_NAMES,
    Fp8Dense,
    fp8_matmul,
    fp8_meta_mask,
    has_fp8_meta,
    recipe_to_config_kwargs,
    wrap_optimizer_for_fp8,
)
from accelerate_tpu.utils.dataclasses import FP8RecipeKwargs


def _fresh_meta(hist_len=8):
    return {
        "input_scale": jnp.ones(()),
        "kernel_scale": jnp.ones(()),
        "grad_scale": jnp.ones(()),
        "input_amax_history": jnp.zeros((hist_len,)),
        "kernel_amax_history": jnp.zeros((hist_len,)),
        "grad_amax_history": jnp.zeros((hist_len,)),
    }


class TestFp8Matmul:
    def test_forward_close_to_bf16(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 32), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (32, 8), jnp.float32)
        y_fp8 = fp8_matmul(x, k, _fresh_meta())
        y_ref = x @ k
        # e4m3 has ~2 decimal digits; unit-scale data quantizes well.
        np.testing.assert_allclose(np.asarray(y_fp8), np.asarray(y_ref), atol=0.5, rtol=0.2)

    def test_gradients_close_to_exact(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (16, 32), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (32, 8), jnp.float32)

        def fp8_loss(x, k):
            return jnp.sum(fp8_matmul(x, k, _fresh_meta()) ** 2) / 100

        def exact_loss(x, k):
            return jnp.sum((x @ k) ** 2) / 100

        gx8, gk8 = jax.grad(fp8_loss, argnums=(0, 1))(x, k)
        gx, gk = jax.grad(exact_loss, argnums=(0, 1))(x, k)
        # e5m2 backward: ~1 decimal digit — directions must agree strongly.
        cos_x = np.dot(np.ravel(gx8), np.ravel(gx)) / (
            np.linalg.norm(gx8) * np.linalg.norm(gx)
        )
        cos_k = np.dot(np.ravel(gk8), np.ravel(gk)) / (
            np.linalg.norm(gk8) * np.linalg.norm(gk)
        )
        assert cos_x > 0.99 and cos_k > 0.99

    def test_meta_cotangent_carries_amax(self):
        x = 3.0 * jax.random.normal(jax.random.PRNGKey(0), (4, 8), jnp.float32)
        k = jax.random.normal(jax.random.PRNGKey(1), (8, 2), jnp.float32)

        def loss(meta):
            return jnp.sum(fp8_matmul(x, k, meta))

        dmeta = jax.grad(loss)(_fresh_meta())
        np.testing.assert_allclose(
            float(dmeta["input_amax_history"][0]), float(jnp.max(jnp.abs(x))), rtol=1e-6
        )
        assert float(dmeta["input_scale"]) > 0

    def test_delayed_scaling_uses_previous_scale(self):
        """Quantization must use the *passed* scale, not the current amax."""
        x = 1000.0 * jnp.ones((2, 4), jnp.float32)
        k = jnp.ones((4, 2), jnp.float32)
        meta = _fresh_meta()
        y = fp8_matmul(x, k, meta)
        # scale=1 clips 1000 -> 448 (e4m3 max): the output shows saturation,
        # proving the fresh amax did NOT feed this step's scale.
        assert float(jnp.max(y)) == pytest.approx(448 * 4, rel=0.01)


class TestFp8Dense:
    def test_trains_and_updates_stats(self):
        x = jax.random.normal(jax.random.PRNGKey(0), (8, 16), jnp.float32)
        m = Fp8Dense(features=4, amax_history_len=4)
        params = m.init(jax.random.PRNGKey(1), x)["params"]
        assert has_fp8_meta(params)
        tx = wrap_optimizer_for_fp8(optax.adam(1e-2), params)
        state = tx.init(params)

        def loss(p):
            return jnp.mean(m.apply({"params": p}, x) ** 2)

        l0 = float(loss(params))
        for _ in range(5):
            g = jax.grad(loss)(params)
            upd, state = tx.update(g, state, params)
            params = optax.apply_updates(params, upd)
        assert float(loss(params)) < l0
        # Statistics were overwritten, not Adam-stepped.
        np.testing.assert_allclose(
            float(params["input_amax_history"][0]), float(jnp.max(jnp.abs(x))), rtol=1e-3
        )
        assert float(params["input_scale"]) == pytest.approx(
            float(jnp.max(jnp.abs(x))) / 448.0, rel=1e-2
        )

    def test_mask_names(self):
        x = jnp.ones((2, 4))
        params = Fp8Dense(features=3).init(jax.random.PRNGKey(0), x)["params"]
        mask = fp8_meta_mask(params)
        assert mask["kernel"] is False
        for name in FP8_META_NAMES:
            assert mask[name] is True


class TestFp8LlamaTraining:
    # One trained run per precision, shared by every assertion in the class:
    # each _train pays a full fused-step compile, and the stats/clip checks
    # hold at any step count >= 3.
    _runs: dict = {}

    def _train(self, use_fp8: bool, steps: int = 8):
        if (use_fp8, steps) in self._runs:
            return self._runs[(use_fp8, steps)]
        from accelerate_tpu.state import AcceleratorState, GradientState, PartialState

        for cls in (AcceleratorState, GradientState, PartialState):
            cls._reset_state()
        cfg = LlamaConfig.tiny(use_flash_attention=False, use_fp8=use_fp8)
        model_def = LlamaForCausalLM(cfg)
        params = model_def.init_params(jax.random.PRNGKey(0), 1, 8)
        acc = Accelerator(
            mixed_precision="fp8" if use_fp8 else "bf16",
            mesh_config=MeshConfig(dp=2, tp=2, devices=jax.devices()[:4]),
        )
        model, opt = acc.prepare(Model(model_def, params), optax.adamw(3e-3))
        step = acc.compile_train_step(causal_lm_loss(model_def.apply), max_grad_norm=1.0)
        ids = np.random.default_rng(0).integers(0, cfg.vocab_size, (4, 16)).astype(np.int32)
        batch = make_global_batch({"input_ids": ids}, acc.mesh)
        out = [float(step(batch)["loss"]) for _ in range(steps)], model
        self._runs[(use_fp8, steps)] = out
        return out

    def test_fp8_converges_close_to_bf16(self):
        losses_fp8, model = self._train(use_fp8=True)
        losses_bf16, _ = self._train(use_fp8=False)
        assert losses_fp8[-1] < losses_fp8[0], "fp8 training must reduce loss"
        # Same model/data/opt: trajectories should track within fp8 noise.
        assert abs(losses_fp8[-1] - losses_bf16[-1]) < 0.15 * losses_bf16[0]

    def test_fp8_stats_flow_under_fused_step(self):
        _, model = self._train(use_fp8=True)
        leaves = jax.tree_util.tree_leaves_with_path(model.params)
        hists = [
            leaf
            for path, leaf in leaves
            if getattr(path[-1], "key", None) == "input_amax_history"
        ]
        assert hists, "fp8 meta params must exist in the trained model"
        # After 3 steps every projection has seen real activations.
        assert all(float(jnp.max(h)) > 0 for h in hists)

    def test_clip_does_not_scale_stats(self):
        """A tiny max_grad_norm must not shrink the overwritten statistics."""
        _, model = self._train(use_fp8=True)
        scales = [
            float(leaf)
            for path, leaf in jax.tree_util.tree_leaves_with_path(model.params)
            if getattr(path[-1], "key", None) == "input_scale"
        ]
        # Activations are O(1): a clipped-through-Adam scale would be ~1e-4
        # after 2 steps; the overwritten value stays at amax/448 rounding.
        assert all(s > 1e-4 for s in scales)


class TestPolicyKeepsStatsFp32:
    def test_cast_to_compute_exempts_fp8_meta(self):
        """Delayed-scaling statistics are fp32 by contract (TE semantics):
        the bf16 compute policy must cast weights but never the six meta
        leaves — rounding them quantizes every scale and trips jax's
        scatter dtype-mismatch (a FutureWarning today, an error soon)."""
        import warnings

        from accelerate_tpu.precision import policy_for

        x = jnp.ones((4, 8), jnp.float32)
        params = Fp8Dense(features=4).init(jax.random.PRNGKey(0), x)["params"]
        cp = policy_for("fp8").cast_to_compute(params)
        assert cp["kernel"].dtype == jnp.bfloat16
        for name in FP8_META_NAMES:
            assert cp[name].dtype == jnp.float32, name
        # The full fwd+bwd under the cast params must be warning-clean.
        with warnings.catch_warnings():
            warnings.simplefilter("error", FutureWarning)
            m = Fp8Dense(features=4)
            jax.grad(lambda p: jnp.sum(m.apply(
                {"params": p}, x.astype(jnp.bfloat16)) ** 2))(cp)


class TestRecipeBridge:
    def test_recipe_to_config(self):
        recipe = FP8RecipeKwargs(margin=2, amax_history_len=32, fp8_format="E4M3")
        kwargs = recipe_to_config_kwargs(recipe)
        cfg = LlamaConfig.tiny(**kwargs)
        assert cfg.use_fp8 and cfg.fp8_margin == 2 and cfg.fp8_format == "E4M3"
