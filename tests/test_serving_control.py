"""SLO-aware control plane (serving.control + the policy hooks in
scheduler/engine/router/gateway/supervisor).

The acceptance-critical properties pinned here:

* PRIORITY ACTED ON — the admission queue is a priority queue (strict
  class order, FIFO within a class, ``putleft`` preserves within-class
  order) and pool-exhaustion preemption evicts the LOWEST class first
  (newest-admitted within the class), not plain newest-admitted; a
  preempted stream resumes token-exact through the prompt+tokens
  readmit path.
* AHEAD-OF-LINE ADMISSION — an interactive request submitted behind
  queued batch work is admitted first.
* WEIGHTED FAIR SHARE + RATE LIMITS — per-tenant token buckets and
  work-conserving fair share shed with STRUCTURED 429s whose
  ``Retry-After`` derives from bucket refill / drain time, clamped
  through the gateway's shared ``[retry_after_s, retry_after_max_s]``
  path, with per-cause shed counters — identically on BOTH front ends
  (the threading-vs-asyncio drift test).
* PREFIX-CACHE-AWARE ROUTING — ``PrefixCache.longest_prefix`` probes
  residency WITHOUT promoting LRU entries, and the router prefers the
  replica holding this prompt's prefix KV over an emptier cold one —
  but never over an idle replica when the cache holder is saturated.
* SUPERVISOR-DRIVEN AUTOSCALING — queue pressure unparks a PARKED
  replica (full rebuild from the retained factory), sustained idleness
  drains and parks the marginal one (two-phase, zero dropped tokens),
  hysteresis and CRASH_LOOP are respected, and the fleet gauges
  (parked/scale_ups/scale_downs/autoscale_events) export on /metrics.
* ZERO RECOMPILES — priority preemption and park/scale traffic compile
  nothing after warmup: every policy decision is host-side bookkeeping.
* CHAOS SOAK — kill + preempt under the supervisor across a
  mixed-priority workload: zero duplicated/lost tokens, balanced
  counters, token-exact preempted-and-resumed streams.
"""

import json
import os
import sys
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402
from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.serving import (  # noqa: E402
    AdmissionQueue,
    AutoscaleConfig,
    ChaosSchedule,
    FairShareAdmission,
    FleetAutoscaler,
    FleetSupervisor,
    GatewayConfig,
    PrefixCache,
    PriorityPolicy,
    ReplicaSet,
    ReplicaState,
    Request,
    RequestStatus,
    ServingEngine,
    ServingGateway,
    TenantRateLimiter,
    TokenBucket,
)
from accelerate_tpu.utils.profiling import CompileWatcher  # noqa: E402

EOS = 7

PROMPTS = [
    np.array([[3, 5, 7, 11, 2]], np.int32),
    np.array([[1, 4, 9]], np.int32),
    np.array([[8, 6, 4, 2, 10, 12, 14]], np.int32),
    np.array([[42]], np.int32),
]


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


def _offline(m, params, prompt, n, eos=EOS):
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=eos)
    return np.asarray(out)[0, prompt.shape[1]:]


def _factory(m, params, **kw):
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("eos_token_id", EOS)
    return lambda: ServingEngine(m, params, **kw)


def _get(url, path, timeout=30):
    try:
        with urllib.request.urlopen(url + path, timeout=timeout) as resp:
            return resp.status, resp.read().decode(), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode(), dict(e.headers)


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url + "/v1/completions", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


# ---------------------------------------------------------------------
# Policy primitives (no engine, fast)
# ---------------------------------------------------------------------
class TestPriorityPolicy:
    def test_default_order_and_fallbacks(self):
        p = PriorityPolicy()
        assert p.rank("interactive") == 0
        assert p.rank("standard") == 1
        assert p.rank("batch") == 2
        # None and unknown names degrade to the default class, so a
        # typo'd class gets normal service, never starvation/dominance.
        assert p.rank(None) == 1
        assert p.rank("no-such-class") == 1

    def test_custom_classes_and_default(self):
        p = PriorityPolicy(("gold", "silver", "bronze"), default="bronze")
        assert p.rank("gold") == 0
        assert p.rank(None) == 2
        # No "standard" and no explicit default -> the middle class.
        assert PriorityPolicy(("a", "b", "c")).rank(None) == 1

    def test_validation(self):
        with pytest.raises(ValueError):
            PriorityPolicy(())
        with pytest.raises(ValueError):
            PriorityPolicy(("a", "a"))
        with pytest.raises(ValueError):
            PriorityPolicy(("a", "b"), default="c")


class TestTokenBucket:
    def test_burst_then_refill(self):
        b = TokenBucket(rate_per_s=1.0, burst=2.0)
        t0 = time.monotonic() + 100.0  # injected clock, after the stamp
        assert b.try_acquire(now=t0)
        assert b.try_acquire(now=t0)
        assert not b.try_acquire(now=t0)
        # Honest Retry-After: exactly the time until one token refills.
        assert b.retry_after(now=t0) == pytest.approx(1.0)
        assert b.retry_after(now=t0 + 0.75) == pytest.approx(0.25)
        assert b.try_acquire(now=t0 + 1.0)
        # Refill caps at burst even after a long idle.
        for _ in range(2):
            assert b.try_acquire(now=t0 + 1000.0)
        assert not b.try_acquire(now=t0 + 1000.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            TokenBucket(0.0, 1.0)


class TestTenantRateLimiter:
    def test_explicit_wildcard_and_unlimited(self):
        lim = TenantRateLimiter({"alice": 1.0, "*": 2.0}, burst_s=1.0)
        # alice: burst of 1 request, then a ~1s retry-after.
        assert lim.admit("alice") is None
        ra = lim.admit("alice")
        assert ra is not None and 0 < ra <= 1.0
        # bob falls to the wildcard bucket (its own bucket, not shared).
        assert lim.admit("bob") is None
        assert lim.admit("bob") is None
        assert lim.admit("bob") is not None
        # carol's wildcard bucket is independent of bob's.
        assert lim.admit("carol") is None

    def test_no_wildcard_means_unlimited(self):
        lim = TenantRateLimiter({"alice": 1.0}, burst_s=1.0)
        for _ in range(50):
            assert lim.admit("bob") is None

    def test_validation(self):
        with pytest.raises(ValueError):
            TenantRateLimiter({"a": 0.0})
        with pytest.raises(ValueError):
            TenantRateLimiter({"a": 1.0}, burst_s=0.0)


class TestFairShareAdmission:
    def test_work_conserving_borrow_under_headroom(self):
        fs = FairShareAdmission({"*": 1.0}, pressure=0.8)
        # One tenant may take ALL idle capacity while under pressure.
        for _ in range(7):
            assert fs.try_acquire("a", capacity=10)
        assert fs.inflight("a") == 7

    def test_over_share_shed_under_pressure_spares_under_share(self):
        fs = FairShareAdmission({"*": 1.0}, pressure=0.5)
        assert fs.try_acquire("b", capacity=10)
        for _ in range(5):
            assert fs.try_acquire("a", capacity=10)
        # Past pressure*capacity with two active tenants: "a" holds 5 =
        # its guaranteed share (equal weights -> 10/2), so its next
        # stream sheds...
        assert not fs.try_acquire("a", capacity=10)
        assert fs.sheds == 1
        # ...while under-share "b" still finds room.
        assert fs.try_acquire("b", capacity=10)
        # Release restores admissibility.
        fs.release("a")
        fs.release("a")
        assert fs.try_acquire("a", capacity=10)

    def test_weights_skew_guarantees(self):
        fs = FairShareAdmission({"big": 3.0, "small": 1.0}, pressure=0.1)
        # Guarantees are over ACTIVE tenants (holders + the applicant):
        # alone, a tenant is guaranteed the whole capacity.
        assert fs.guaranteed("big", 8) == 8
        assert fs.try_acquire("big", 8)
        assert fs.guaranteed("small", 8) == 2  # 1/4 of 8 vs active big
        assert fs.try_acquire("small", 8)
        assert fs.guaranteed("big", 8) == 6    # 3/4 of 8

    def test_validation(self):
        with pytest.raises(ValueError):
            FairShareAdmission({"a": -1.0})
        with pytest.raises(ValueError):
            FairShareAdmission({}, pressure=0.0)


class TestAutoscaleConfig:
    def test_validation(self):
        with pytest.raises(ValueError):
            AutoscaleConfig(min_replicas=0)
        with pytest.raises(ValueError):
            AutoscaleConfig(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscaleConfig(scale_up_queue_depth=0.0)
        with pytest.raises(ValueError):
            AutoscaleConfig(idle_load=1.0)


# ---------------------------------------------------------------------
# Priority queue + prefix probe (no engine, fast)
# ---------------------------------------------------------------------
def _req(priority=None, tag=0):
    return Request(np.array([[tag + 1]], np.int32), max_new_tokens=4,
                   priority=priority)


class TestPriorityAdmissionQueue:
    def test_strict_class_order_fifo_within(self):
        q = AdmissionQueue(16, rank_fn=PriorityPolicy().rank)
        b1, b2 = _req("batch", 1), _req("batch", 2)
        s1 = _req(None, 3)          # None -> standard
        i1, i2 = _req("interactive", 4), _req("interactive", 5)
        for r in (b1, b2, s1, i1, i2):
            q.put(r)
        assert [q.get() for _ in range(5)] == [i1, i2, s1, b1, b2]

    def test_putleft_rejoins_own_class_front_never_jumps_up(self):
        q = AdmissionQueue(16, rank_fn=PriorityPolicy().rank)
        i1 = _req("interactive", 1)
        b1, b2 = _req("batch", 2), _req("batch", 3)
        for r in (b1, b2, i1):
            q.put(r)
        # A preempted batch request goes back ahead of younger BATCH
        # work but still behind every interactive request.
        q.putleft(b1)  # simulate: b1 was popped earlier, now preempted
        assert q.get() is i1
        assert q.get() is b1
        assert q.get() is b1  # the still-queued original instance
        assert q.get() is b2

    def test_no_rank_fn_is_plain_fifo(self):
        q = AdmissionQueue(16)
        rs = [_req("interactive", 1), _req("batch", 2), _req(None, 3)]
        for r in rs:
            q.put(r)
        assert [q.get() for _ in range(3)] == rs


class TestLongestPrefixProbe:
    def test_counts_leading_resident_without_lru_touch(self):
        c = PrefixCache(capacity_bytes=3)
        c.put(b"k0", "b0", 1)
        c.put(b"k1", "b1", 1)
        c.put(b"k2", "b2", 1)
        assert c.longest_prefix([b"k0", b"k1", b"k2"]) == 3
        assert c.longest_prefix([b"k0", b"kX", b"k2"]) == 1  # chain stops
        assert c.longest_prefix([b"kX"]) == 0
        # The probe must NOT promote: k0 is still the LRU entry, so the
        # next insert at capacity evicts k0 — not k1 (which a promoting
        # probe would have left least-recent).
        c.longest_prefix([b"k0", b"k1"])
        c.put(b"k3", "b3", 1)
        assert c.longest_prefix([b"k0"]) == 0
        assert c.longest_prefix([b"k1"]) == 1
        # match() DOES promote (it restores the blocks): k1 to MRU, so
        # the next eviction takes k2.
        c.match([b"k1"])
        c.put(b"k4", "b4", 1)
        assert c.longest_prefix([b"k1"]) == 1
        assert c.longest_prefix([b"k2"]) == 0


# ---------------------------------------------------------------------
# Engine hooks: victim selection, ahead-of-line, cache probe
# ---------------------------------------------------------------------
class TestEnginePriorityHooks:
    def test_priority_policy_arg_validated(self, tiny):
        _, m, params = tiny
        with pytest.raises(TypeError, match="priority_policy"):
            ServingEngine(m, params, priority_policy="interactive-first")

    def test_ahead_of_line_admission(self, tiny):
        """With the single decode slot occupied, an interactive request
        submitted BEHIND two queued batch requests is admitted first;
        the batch pair keeps its FIFO order."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=64,
                            eos_token_id=EOS, max_queued=8)
        try:
            blocker = eng.submit(PROMPTS[0], max_new_tokens=24,
                                 ignore_eos=True)
            b1 = eng.submit(PROMPTS[1], max_new_tokens=4, priority="batch")
            b2 = eng.submit(PROMPTS[2], max_new_tokens=4, priority="batch")
            it = eng.submit(PROMPTS[3], max_new_tokens=4,
                            priority="interactive")
            for r in (blocker, b1, b2, it):
                assert r.wait(timeout=120)
            assert it.admitted_at < b1.admitted_at < b2.admitted_at
        finally:
            eng.shutdown(drain=False)

    def test_fcfs_opt_out_keeps_submission_order(self, tiny):
        """priority_policy=None (the A/B baseline): priority is measured
        but NOT acted on — admission stays submission-ordered."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=64,
                            eos_token_id=EOS, max_queued=8,
                            priority_policy=None)
        try:
            blocker = eng.submit(PROMPTS[0], max_new_tokens=24,
                                 ignore_eos=True)
            b1 = eng.submit(PROMPTS[1], max_new_tokens=4, priority="batch")
            it = eng.submit(PROMPTS[3], max_new_tokens=4,
                            priority="interactive")
            for r in (blocker, b1, it):
                assert r.wait(timeout=120)
            assert b1.admitted_at < it.admitted_at
        finally:
            eng.shutdown(drain=False)

    def test_preemption_evicts_lowest_class_and_resumes_exact(self, tiny):
        """Three co-resident streams — two batch admitted first, one
        interactive admitted LAST — against a pool that cannot hold all
        three worst-case footprints (3 x 6 pages vs 12). The victim of
        the decode-time exhaustion must be a BATCH stream even though
        the interactive one is the newest admitted (the inversion of the
        historical newest-admitted rule: the requester is excluded and
        any batch candidate outranks interactive for eviction), and
        after that eviction the survivors (6 + 6 pages) exactly fit, so
        the interactive stream can never be evicted. Everyone finishes
        token-identical to its uninterrupted offline reference."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0, max_pages=12)
        n = 40
        try:
            refs = [_offline(m, params, p, n, eos=None)
                    for p in PROMPTS[:3]]
            batch = [eng.submit(p, max_new_tokens=n, ignore_eos=True,
                                priority="batch") for p in PROMPTS[:2]]
            deadline = time.monotonic() + 60
            while any(r.status is RequestStatus.QUEUED for r in batch) \
                    and time.monotonic() < deadline:
                time.sleep(0.002)
            first_admits = [r.admitted_at for r in batch]
            ri = eng.submit(PROMPTS[2], max_new_tokens=n, ignore_eos=True,
                            priority="interactive")
            for r, ref in zip(batch + [ri], refs):
                got = np.asarray(r.result(timeout=180))
                assert np.array_equal(got, ref), (got, ref)
            assert eng.stats.summary()["preemptions"] >= 1
            assert sum(r._preempted for r in batch) >= 1, (
                "a batch stream must be the preemption victim")
            assert ri._preempted == 0, (
                "the interactive stream must never be evicted while a "
                "batch stream holds a slot, despite being newest-admitted")
            # ...and it really was the newest admission at eviction time
            # (the victim's admitted_at re-stamps on resume, so compare
            # against the stamps captured before ri was submitted).
            assert all(ri.admitted_at > t for t in first_admits)
        finally:
            eng.shutdown(drain=False)

    def test_cached_prefix_tokens_probe(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=96,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=4.0)
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, 256, size=(1, 33)).astype(np.int32)
        other = rng.integers(0, 256, size=(1, 33)).astype(np.int32)
        try:
            assert eng.cached_prefix_tokens(prompt) == 0
            eng.submit(prompt, max_new_tokens=4).result(timeout=120)
            # 33 tokens = 4 full chunks of 8, all restorable.
            assert eng.cached_prefix_tokens(prompt) == 32
            assert eng.cached_prefix_tokens(other) == 0
            # Short prompts (< one restorable chunk) probe as 0.
            assert eng.cached_prefix_tokens(PROMPTS[0]) == 0
        finally:
            eng.shutdown(drain=False)


class TestCacheAwareRouting:
    def test_prefers_cache_holder_unless_saturated(self, tiny):
        """The replica holding this prompt's prefix KV wins routing over
        an idler cold replica — but a SATURATED cache holder loses to
        any replica with a free slot."""
        _, m, params = tiny
        make = _factory(m, params, prefill_chunk=8, max_len=96,
                        prefix_cache_mb=4.0)
        rs = ReplicaSet.from_factory(make, 2)
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, 256, size=(1, 33)).astype(np.int32)
        try:
            # Warm replica 0's cache directly.
            rs.replicas[0].engine.submit(
                prompt, max_new_tokens=4).result(timeout=120)
            # Cold routing signal: replica 1 is emptier once replica 0
            # is busy — without the prompt, it wins.
            blocker = rs.replicas[0].engine.submit(
                PROMPTS[0], max_new_tokens=40, ignore_eos=True)
            assert rs._candidates()[0].index == 1
            # With the prompt, the cached prefix dominates free slots.
            assert rs._candidates(prompt_ids=prompt)[0].index == 0
            # Saturate replica 0 entirely: cache affinity must NOT queue
            # behind it while replica 1 has free slots.
            blocker2 = rs.replicas[0].engine.submit(
                PROMPTS[1], max_new_tokens=40, ignore_eos=True)
            deadline = time.monotonic() + 60
            while rs.replicas[0].engine.free_slots > 0 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            assert rs._candidates(prompt_ids=prompt)[0].index == 1
            for b in (blocker, blocker2):
                b.wait(timeout=120)
        finally:
            rs.shutdown(drain=False)


# ---------------------------------------------------------------------
# Gateway policy: rate limit + fair share, on BOTH front ends
# ---------------------------------------------------------------------
@pytest.mark.parametrize("server", ["threading", "asyncio"])
class TestGatewayPolicy:
    """Every test runs against both front ends — the drift test: the
    policy lives in the shared ``submit_or_error`` path, so status
    codes, payload shapes, Retry-After clamping, and shed counters must
    be identical."""

    def test_rate_limit_429_structured_clamped_counted(self, tiny, server):
        _, m, params = tiny
        rs = ReplicaSet.from_factory(_factory(m, params), 1)
        cfg = GatewayConfig(server=server, port=0,
                            rate_limits={"*": 0.5}, rate_limit_burst_s=2.0,
                            retry_after_s=1.5, retry_after_max_s=60.0)
        try:
            with ServingGateway(rs, config=cfg) as gw:
                body = {"prompt": [3, 5, 7], "max_new_tokens": 2}
                code, _, _ = _post(gw.url, body)  # burst = 1 token
                assert code == 200
                code, payload, headers = _post(gw.url, body)
                assert code == 429
                assert payload["error"] == "rate_limited"
                assert payload["tenant"] == "_base"
                # Raw refill time (~2s) clamped into the shared window.
                retry = float(headers["Retry-After"])
                assert cfg.retry_after_s <= retry <= cfg.retry_after_max_s
                code, text, _ = _get(gw.url, "/metrics")
                assert "accelerate_tpu_gateway_rate_limit_sheds 1" in text
                assert gw.stats.summary()["rate_limit_sheds"] == 1
        finally:
            rs.shutdown(drain=False)

    def test_fair_share_429_release_on_done(self, tiny, server):
        """A sole tenant past its guaranteed share under pressure sheds
        with a structured 429; once its streams finish (the done
        callback releases the share) it admits again."""
        _, m, params = tiny
        m_slow = bench._sleepy_llama_cls(step_ms=15.0)(LlamaConfig.tiny(
            use_flash_attention=False))
        rs = ReplicaSet.from_factory(
            _factory(m_slow, params, max_slots=1, max_queued=1), 1)
        cfg = GatewayConfig(server=server, port=0,
                            fair_share_weights={"*": 1.0},
                            fair_share_pressure=0.85,
                            retry_after_s=1.0, retry_after_max_s=60.0)
        try:
            with ServingGateway(rs, config=cfg) as gw:
                assert rs.admission_capacity() == 2  # 1 slot + 1 queued
                streams = []
                for p in PROMPTS[:2]:  # hold capacity via open SSE
                    req = urllib.request.Request(
                        gw.url + "/v1/completions",
                        data=json.dumps({
                            "prompt": p[0].tolist(), "stream": True,
                            "max_new_tokens": 40,
                            "ignore_eos": True}).encode(),
                        headers={"Content-Type": "application/json"})
                    streams.append(urllib.request.urlopen(req, timeout=60))
                    # Let the first stream reach the decode slot before
                    # opening the second, so #2 lands in the queue (not
                    # a QueueFull 429 behind a still-queued #1).
                    deadline = time.monotonic() + 30
                    while rs.replicas[0].engine.free_slots > 0 \
                            and time.monotonic() < deadline:
                        time.sleep(0.005)
                code, payload, headers = _post(
                    gw.url, {"prompt": [1, 2], "max_new_tokens": 2})
                assert code == 429
                assert payload["error"] == "fair_share_exceeded"
                retry = float(headers["Retry-After"])
                assert cfg.retry_after_s <= retry <= cfg.retry_after_max_s
                code, text, _ = _get(gw.url, "/metrics")
                assert "accelerate_tpu_gateway_fair_share_sheds 1" in text
                for s in streams:  # drain: done callbacks release shares
                    s.read()
                    s.close()
                deadline = time.monotonic() + 30
                while gw.fair_share.inflight() > 0 \
                        and time.monotonic() < deadline:
                    time.sleep(0.005)
                assert gw.fair_share.inflight() == 0
                code, _, _ = _post(gw.url,
                                   {"prompt": [1, 2], "max_new_tokens": 2})
                assert code == 200, "released shares must re-admit"
        finally:
            rs.shutdown(drain=False)


# ---------------------------------------------------------------------
# Autoscaler: closed loop over PARKED replicas
# ---------------------------------------------------------------------
class TestAutoscaler:
    def test_queue_pressure_unparks_then_idle_drains_and_parks(self, tiny):
        _, m, params = tiny
        make = _factory(m, params, max_slots=1, max_queued=8)
        rs = ReplicaSet.from_factory(make, 1)
        idx = rs.add_parked(make)
        assert rs.replicas[idx].state is ReplicaState.PARKED
        auto = FleetAutoscaler(rs, AutoscaleConfig(
            min_replicas=1, max_replicas=2, scale_up_queue_depth=2.0,
            scale_down_idle_s=0.5, idle_load=0.0, cooldown_s=0.0))
        t0 = time.monotonic()
        try:
            # No pressure -> no action (and no spurious scale-down yet).
            assert auto.step(now=t0) is None
            blocker = rs.submit(PROMPTS[0], max_new_tokens=40,
                                ignore_eos=True)
            queued = [rs.submit(PROMPTS[i % 4], max_new_tokens=4)
                      for i in range(1, 4)]
            deadline = time.monotonic() + 30
            while len(rs.replicas[0].engine._queue) < 2 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            assert auto.step(now=t0 + 1.0) == "up"
            assert rs.replicas[idx].state is ReplicaState.HEALTHY
            assert auto.scale_ups == 1
            assert [e["kind"] for e in auto.events()] == ["scale_up"]
            for r in [blocker] + queued:
                r.wait(timeout=120)
            # Sustained idleness: first step arms idle_since, a later
            # one (past scale_down_idle_s) drains the marginal replica,
            # a third parks it once empty — two-phase, no token drops.
            t1 = time.monotonic() + 10.0
            assert auto.step(now=t1) is None
            assert auto.step(now=t1 + 1.0) == "down"
            assert rs.replicas[idx].state is ReplicaState.DRAINING
            assert auto.step(now=t1 + 1.1) == "parked"
            assert rs.replicas[idx].state is ReplicaState.PARKED
            assert auto.scale_downs == 1
            fm = rs.fleet_metrics()
            assert fm["replicas_parked"] == 1
            assert fm["fleet_scale_ups"] == 1
            assert fm["fleet_scale_downs"] == 1
            assert fm["fleet_autoscale_events"] == 2
            # ...and the gauges ride the /metrics exposition.
            gw = ServingGateway(rs, config=GatewayConfig(port=0))
            text = gw.metrics_text()
            for name in ("accelerate_tpu_serving_replicas_parked 1",
                         "accelerate_tpu_serving_fleet_scale_ups 1",
                         "accelerate_tpu_serving_fleet_scale_downs 1",
                         "accelerate_tpu_serving_fleet_autoscale_events 2"):
                assert name in text, name
            # Never below min_replicas, no matter how long the idle.
            assert auto.step(now=t1 + 100.0) is None
            assert auto.step(now=t1 + 200.0) is None
            assert rs.replicas[0].state is ReplicaState.HEALTHY
        finally:
            rs.shutdown(drain=False)

    def test_cooldown_and_crash_loop_respected(self, tiny):
        _, m, params = tiny
        make = _factory(m, params, max_slots=1, max_queued=8)
        rs = ReplicaSet.from_factory(make, 1)
        idx = rs.add_parked(make)
        auto = FleetAutoscaler(rs, AutoscaleConfig(
            min_replicas=1, max_replicas=2, scale_up_queue_depth=1.0,
            cooldown_s=30.0))
        t0 = time.monotonic()
        try:
            blocker = rs.submit(PROMPTS[0], max_new_tokens=40,
                                ignore_eos=True)
            queued = [rs.submit(PROMPTS[1], max_new_tokens=4)
                      for _ in range(2)]
            deadline = time.monotonic() + 30
            while len(rs.replicas[0].engine._queue) < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            # A CRASH_LOOP replica is invisible to scale-up: the breaker
            # verdict stands even under pressure.
            rs.replicas[idx].state = ReplicaState.CRASH_LOOP
            assert auto.step(now=t0 + 100.0) is None
            rs.replicas[idx].state = ReplicaState.PARKED
            assert auto.step(now=t0 + 100.0) == "up"
            # Straight back under pressure: cooldown blocks action #2.
            rs.park_replica  # (no-op reference; replica 1 may be busy)
            assert auto.step(now=t0 + 101.0) is None
            for r in [blocker] + queued:
                r.wait(timeout=120)
        finally:
            rs.shutdown(drain=False)

    def test_supervisor_drives_the_loop(self, tiny):
        """FleetSupervisor(autoscaler=...) folds a policy step into each
        watchdog scan: queue pressure during check_once unparks."""
        _, m, params = tiny
        make = _factory(m, params, max_slots=1, max_queued=8)
        rs = ReplicaSet.from_factory(make, 1)
        idx = rs.add_parked(make)
        auto = FleetAutoscaler(rs, AutoscaleConfig(
            min_replicas=1, max_replicas=2, scale_up_queue_depth=1.0,
            cooldown_s=0.0))
        other = ReplicaSet.from_factory(make, 1)
        try:
            with pytest.raises(ValueError, match="different ReplicaSet"):
                FleetSupervisor(other, autoscaler=auto)
            sup = FleetSupervisor(rs, autoscaler=auto)
            blocker = rs.submit(PROMPTS[0], max_new_tokens=40,
                                ignore_eos=True)
            queued = [rs.submit(PROMPTS[1], max_new_tokens=4)
                      for _ in range(2)]
            deadline = time.monotonic() + 30
            while len(rs.replicas[0].engine._queue) < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            sup.check_once()
            assert rs.replicas[idx].state is ReplicaState.HEALTHY
            assert auto.scale_ups == 1
            for r in [blocker] + queued:
                r.wait(timeout=120)
        finally:
            other.shutdown(drain=False)
            rs.shutdown(drain=False)


# ---------------------------------------------------------------------
# Zero-recompile pins: policy is host-side bookkeeping
# ---------------------------------------------------------------------
class TestZeroRecompileControl:
    def test_priority_preemption_compiles_nothing(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=0.0, max_pages=10)
        try:
            with CompileWatcher() as watcher:
                rb = eng.submit(PROMPTS[0], max_new_tokens=40,
                                ignore_eos=True, priority="batch")
                ri = eng.submit(PROMPTS[1], max_new_tokens=40,
                                ignore_eos=True, priority="interactive")
                for r in (rb, ri):
                    r.result(timeout=180)
            assert eng.stats.summary()["preemptions"] >= 1
        finally:
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — victim "
            "selection and priority admission are host-side policy only")

    def test_park_and_post_unpark_traffic_compile_nothing(self, tiny):
        _, m, params = tiny
        make = _factory(m, params)
        rs = ReplicaSet.from_factory(make, 2)
        try:
            # Scale-down (park) is pure teardown + traffic on the
            # surviving replica reuses its warm executables.
            with CompileWatcher() as watcher:
                rs.park_replica(1)
                rs.submit(PROMPTS[0], max_new_tokens=6).wait(timeout=120)
            assert not watcher.events, (
                f"XLA recompiled on park: {watcher.events}")
            # Unpark rebuilds+warms replica 1 (compiles, by design,
            # OUTSIDE the watch); traffic after it is warm everywhere.
            rs.unpark_replica(1)
            with CompileWatcher() as watcher:
                reqs = [rs.submit(PROMPTS[i % 4], max_new_tokens=6)
                        for i in range(4)]
                for r in reqs:
                    r.wait(timeout=120)
            assert not watcher.events, (
                f"XLA recompiled after unpark warmup: {watcher.events}")
        finally:
            rs.shutdown(drain=False)


# ---------------------------------------------------------------------
# Chaos soak: kill + preempt under the supervisor, mixed priorities
# ---------------------------------------------------------------------
class TestMixedPriorityChaosSoak:
    @pytest.mark.slow
    def test_soak_zero_dup_lost_tokens_balanced_counters(self, tiny):
        """Scripted replica kill + organic pool-exhaustion preemption
        while a 24-request mixed-priority workload runs under the
        supervisor: every stream (including the preempted-and-resumed
        and the killed-and-failed-over ones) finishes token-identical
        to its uninterrupted offline reference, and the fleet-merged
        counters stay balanced across the restart."""
        _, m, params = tiny
        make = _factory(m, params, max_slots=3, max_len=64,
                        prefill_chunk=8, prefix_cache_mb=0.0, max_pages=14)
        chaos_kill = ChaosSchedule().kill(at_tick=10)
        rs = ReplicaSet(
            [ServingEngine(m, params, max_slots=3, max_len=64,
                           eos_token_id=EOS, prefill_chunk=8,
                           prefix_cache_mb=0.0, max_pages=14,
                           chaos=chaos_kill),
             make()],
            factories=[make, make])
        N = 24
        classes = ["interactive", "batch", None, "batch"]
        prompts = [PROMPTS[i % len(PROMPTS)] for i in range(N)]
        lengths = [24 + (i % 2) * 16 for i in range(N)]  # 24/40 mixed
        refs = [_offline(m, params, p, n, eos=None)
                for p, n in zip(prompts, lengths)]
        try:
            with FleetSupervisor(rs, hang_timeout_s=5.0,
                                 poll_interval_s=0.02,
                                 restart_backoff_s=0.05) as sup:
                reqs = [rs.submit(p, max_new_tokens=n, ignore_eos=True,
                                  priority=classes[i % len(classes)])
                        for i, (p, n) in enumerate(zip(prompts, lengths))]
                for r in reqs:
                    assert r.wait(timeout=300)
                for i, (r, ref) in enumerate(zip(reqs, refs)):
                    assert r.status is RequestStatus.COMPLETED, (i, r)
                    got = np.asarray(r.tokens)
                    assert np.array_equal(got, ref), (i, got, ref)
                assert "kill" in chaos_kill.fired()
                # The undersized pools forced real preemptions and the
                # kill forced real failovers — the soak exercised both.
                merged = rs.merged_stats().summary()
                assert merged["preemptions"] >= 1, merged
                assert rs.fleet_metrics()["fleet_failovers"] >= 1
                deadline = time.monotonic() + 120
                while sup.restarts < 1 and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert sup.restarts >= 1, sup.events()
                # Counters balance: every submitted request is accounted
                # for exactly once across terminal states.
                merged = rs.merged_stats().summary()
                assert merged["requests_completed"] >= N
                assert (merged["requests_submitted"]
                        >= merged["requests_completed"]
                        + merged["requests_failed"])
        finally:
            rs.shutdown(drain=False)
