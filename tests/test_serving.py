"""Continuous-batching serving engine (serving.ServingEngine).

The acceptance-critical properties pinned here:

* EXACTNESS — tokens streamed by the engine are bit-identical to offline
  ``generation.generate`` for the same (prompt, rng, sampling), including
  eos semantics, even when requests join mid-flight of other requests'
  decode loops (staggered arrivals exercise the slot mask, not the shape).
* ZERO RECOMPILES — after warmup, admitting and retiring requests of
  varying prompt lengths triggers no new XLA compilation (probed via
  jax.monitoring's event-duration listener, which fires per compile);
  with chunked prefill the steady state is exactly ONE executable each
  for prefill_chunk, restore_prefix, and decode, whatever prompt-length
  mix arrives.
* CHUNKED PREFILL — chunk-size x prompt-length x sampling exactness
  against both the monolithic engine and offline generate, decode ticks
  interleaving with a long prompt's chunk calls, and the prefix cache
  (unit LRU semantics + a repeat prompt admitting in one chunk).
* SCHEDULING SEMANTICS — bounded-queue backpressure, cancel (queued and
  running), per-request timeout (queued and running), error isolation
  (a raising stream callback fails only its own request), FCFS admission.
* LIFECYCLE — graceful drain on shutdown (plus async-checkpoint flush),
  preemption cooperation (finish in-flight, cancel queued, exit).

All engines share the module-scoped tiny Llama from test_generation.py's
convention; the slow-motion engine uses bench's deterministic-sleep model
so timing-sensitive tests don't depend on host speed.
"""

import os
import sys
import threading
import time
import types

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from accelerate_tpu import generation  # noqa: E402
from accelerate_tpu.models.llama import LlamaConfig, LlamaForCausalLM  # noqa: E402
from accelerate_tpu.utils.profiling import CompileWatcher  # noqa: E402
from accelerate_tpu.serving import (  # noqa: E402
    AdmissionQueue,
    PrefixCache,
    QueueClosed,
    QueueFull,
    Request,
    RequestStatus,
    ServingEngine,
    ServingStats,
    SlotScheduler,
)

EOS = 7

PROMPTS = [
    np.array([[3, 5, 7, 11, 2]], np.int32),
    np.array([[1, 4, 9]], np.int32),
    np.array([[8, 6, 4, 2, 10, 12, 14]], np.int32),
    np.array([[42]], np.int32),
]


@pytest.fixture(scope="module")
def tiny():
    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = LlamaForCausalLM(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
    return cfg, m, params


@pytest.fixture(scope="module")
def engine(tiny):
    """Shared greedy engine (warmup paid once for the whole module)."""
    _, m, params = tiny
    eng = ServingEngine(m, params, max_slots=3, max_len=64, eos_token_id=EOS)
    yield eng
    if eng.running:
        eng.shutdown(drain=False)


@pytest.fixture(scope="module")
def sampled_engine(tiny):
    _, m, params = tiny
    eng = ServingEngine(m, params, max_slots=3, max_len=64, eos_token_id=EOS,
                        do_sample=True, temperature=0.9, top_k=50)
    yield eng
    if eng.running:
        eng.shutdown(drain=False)


@pytest.fixture(scope="module")
def slow_engine():
    """Engine over bench's deterministic-sleep model: ~10 ms per forward,
    so slot-occupancy windows are wide enough for race-free scheduling
    tests on any host."""
    import bench

    cfg = LlamaConfig.tiny(use_flash_attention=False)
    m = bench._sleepy_llama_cls(step_ms=10.0)(cfg)
    params = m.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
    eng = ServingEngine(m, params, max_slots=1, max_len=32, max_queued=1)
    yield eng
    if eng.running:
        eng.shutdown(drain=False)


def _offline(m, params, prompt, n, seed=None, **kw):
    """Offline reference completion [n] (padded with eos past the latch)."""
    rng = None if seed is None else jax.random.PRNGKey(seed)
    out = generation.generate(m, params, prompt, max_new_tokens=n,
                              eos_token_id=EOS, rng=rng, **kw)
    return np.asarray(out)[0, prompt.shape[1]:]


def _assert_matches_offline(got, ref, n):
    """Engine stops AT eos; offline keeps the shape and pads with eos."""
    got = np.asarray(got)
    assert np.array_equal(got, ref[: len(got)]), (got, ref)
    if len(got) < n:
        assert got[-1] == EOS and np.all(ref[len(got):] == EOS), (got, ref)


class TestSchedulerUnits:
    def test_admission_queue_backpressure(self):
        q = AdmissionQueue(max_queued=2)
        a, b = Request([[1]]), Request([[2]])
        q.put(a, block=False)
        q.put(b, block=False)
        with pytest.raises(QueueFull):
            q.put(Request([[3]]), block=False)
        with pytest.raises(QueueFull):
            q.put(Request([[3]]), block=True, timeout=0.01)
        assert q.get_nowait() is a  # FCFS
        assert q.drain() == [b] and len(q) == 0

    def test_slot_scheduler_free_list(self):
        s = SlotScheduler(2)
        r0, r1 = Request([[1]]), Request([[2]])
        assert s.assign(r0) == 0 and s.assign(r1) == 1  # lowest-index-first
        assert not s.has_free() and s.active() == [(0, r0), (1, r1)]
        assert s.release(0) is r0 and r0.slot is None
        r2 = Request([[3]])
        assert s.assign(r2) == 0  # freed slot is reused
        assert s.occupant(0) is r2 and s.active_slots == 2

    def test_request_validation(self):
        with pytest.raises(ValueError, match="max_new_tokens"):
            Request([[1]], max_new_tokens=0)
        with pytest.raises(ValueError, match="prompt_ids"):
            Request(np.zeros((2, 3), np.int32))  # batched prompts: one per slot
        with pytest.raises(ValueError, match="prompt_ids"):
            Request(np.zeros((1, 1, 3), np.int32))
        r = Request([1, 2, 3])  # 1-D promotes to [1, S]
        assert r.prompt_ids.shape == (1, 3)

    def test_request_result_semantics(self):
        r = Request([[1]])
        with pytest.raises(TimeoutError):
            r.result(timeout=0.01)
        r._finish(RequestStatus.CANCELLED)
        with pytest.raises(RuntimeError, match="cancelled"):
            r.result()
        r2 = Request([[1]])
        r2.tokens.extend([4, 5])
        r2._finish(RequestStatus.COMPLETED)
        r2._finish(RequestStatus.FAILED, RuntimeError("late"))  # first wins
        assert r2.status is RequestStatus.COMPLETED
        np.testing.assert_array_equal(r2.result(), [4, 5])
        np.testing.assert_array_equal(r2.output_ids(), [[1, 4, 5]])

    def test_prefix_cache_lru_and_bounds(self):
        with pytest.raises(ValueError, match="capacity_bytes"):
            PrefixCache(0)
        pc = PrefixCache(capacity_bytes=100)
        pc.put(b"a", "A", 40)
        pc.put(b"b", "B", 40)
        assert pc.match([b"a", b"b"]) == ["A", "B"]
        # The chain stops at the first miss: a later chunk's KV is only
        # valid stacked on every earlier one.
        assert pc.match([b"a", b"x", b"b"]) == ["A"]
        pc.put(b"c", "C", 40)  # 120 > 100: evicts the LRU entry (b)
        assert pc.match([b"b"]) == []
        assert pc.match([b"a"]) == ["A"] and pc.match([b"c"]) == ["C"]
        assert len(pc) == 2 and pc.nbytes == 80
        assert pc.insertions == 3 and pc.evictions == 1
        pc.put(b"huge", "H", 1000)  # bigger than the whole budget: skipped
        assert pc.match([b"huge"]) == [] and pc.nbytes == 80
        pc.put(b"a", "A2", 40)  # re-put touches, never duplicates
        assert len(pc) == 2 and pc.match([b"a"]) == ["A"]
        pc.clear()
        assert len(pc) == 0 and pc.nbytes == 0 and pc.match([b"a"]) == []

    def test_stats_summary(self):
        st = ServingStats()
        st.record_submit(queue_depth=3)
        st.record_admit(queue_wait_ms=4.0, ttft_ms=10.0)
        st.record_tick(active_slots=2, committed_tokens=2, max_slots=4, seconds=0.01)
        st.record_finish(RequestStatus.COMPLETED)
        s = st.summary()
        assert s["requests_submitted"] == s["requests_completed"] == 1
        assert s["queue_wait_ms"] == 4.0 and s["ttft_ms_p50"] == 10.0
        assert s["slot_occupancy"] == 0.5 and s["batch_efficiency"] == 0.5
        assert s["tokens_emitted"] == 3  # 1 prefill + 2 decode
        assert s["decode_tokens_per_sec"] == pytest.approx(200.0)
        st.reset()
        assert st.summary()["requests_submitted"] == 0


class TestExactness:
    def test_greedy_staggered_matches_offline(self, engine, tiny):
        """Four requests (one more than there are slots) joining mid-flight:
        every stream must equal offline greedy generate token for token."""
        _, m, params = tiny
        n = 10
        reqs = []
        for p in PROMPTS:
            reqs.append(engine.submit(p, max_new_tokens=n))
            time.sleep(0.015)  # staggered: later prompts join a live batch
        for p, r in zip(PROMPTS, reqs):
            _assert_matches_offline(r.result(timeout=120),
                                    _offline(m, params, p, n), n)

    def test_sampled_staggered_matches_offline(self, sampled_engine, tiny):
        """Same but sampled: per-request seeds must reproduce the offline
        rng chain (split-for-prefill, then split-per-step) exactly."""
        _, m, params = tiny
        n = 10
        reqs = []
        for i, p in enumerate(PROMPTS):
            reqs.append(sampled_engine.submit(p, max_new_tokens=n, seed=100 + i))
            time.sleep(0.015)
        for i, (p, r) in enumerate(zip(PROMPTS, reqs)):
            ref = _offline(m, params, p, n, seed=100 + i,
                           do_sample=True, temperature=0.9, top_k=50)
            _assert_matches_offline(r.result(timeout=120), ref, n)

    def test_max_new_tokens_one_completes_at_prefill(self, engine, tiny):
        _, m, params = tiny
        p = PROMPTS[0]
        r = engine.submit(p, max_new_tokens=1)
        out = r.result(timeout=120)
        assert out.shape == (1,)
        assert out[0] == _offline(m, params, p, 1)[0]

    def test_streaming_callback_order(self, engine):
        streamed = []
        r = engine.submit(PROMPTS[1], max_new_tokens=6,
                          on_token=streamed.append)
        out = r.result(timeout=120)
        assert streamed == list(out)


class TestZeroRecompile:
    def test_no_compiles_after_warmup(self, engine):
        """The acceptance bar: once warmed, admitting/retiring requests of
        DIFFERENT prompt lengths into different slots runs only the two
        existing executables — jax.monitoring's per-compile events must
        stay silent across a full staggered round."""
        with CompileWatcher() as watcher:
            reqs = []
            for i, p in enumerate(PROMPTS):
                reqs.append(engine.submit(p, max_new_tokens=6, seed=7 + i))
                time.sleep(0.01)
            for r in reqs:
                r.result(timeout=120)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — continuous "
            "batching must change mask/state contents, never program shapes")


class TestChunkedExactness:
    """Chunked prefill changes WHEN prompt KV is written, never what is
    written: every (chunk size, prompt length, sampling) cell must be
    token-identical to the monolithic engine AND offline generate —
    including non-multiple tails, single-chunk prompts, and S=1."""

    CHUNKS = (4, 16)
    LENS = (1, 5, 16, 23, 31)  # < C, non-multiples, == C, and multi-chunk

    @pytest.fixture(scope="class")
    def engines(self, tiny):
        _, m, params = tiny
        engs = {"mono": ServingEngine(m, params, max_slots=2, max_len=64,
                                      eos_token_id=EOS, prefill_chunk=None,
                                      warmup=False)}
        for C in self.CHUNKS:
            engs[C] = ServingEngine(m, params, max_slots=2, max_len=64,
                                    eos_token_id=EOS, prefill_chunk=C,
                                    prefix_cache_mb=0.0, warmup=False)
        yield engs
        for e in engs.values():
            if e.running:
                e.shutdown(drain=False)

    def test_greedy_chunk_matrix(self, engines, tiny):
        _, m, params = tiny
        n = 8
        rng = np.random.default_rng(11)
        for C in self.CHUNKS:
            for S in self.LENS:
                p = rng.integers(0, 256, size=(1, S)).astype(np.int32)
                before = engines[C].serving_metrics()["prefill_chunks"]
                got_c = engines[C].submit(p, max_new_tokens=n).result(timeout=120)
                chunks = engines[C].serving_metrics()["prefill_chunks"] - before
                assert chunks == -(-S // C), (S, C, chunks)  # really chunked
                got_m = engines["mono"].submit(p, max_new_tokens=n).result(timeout=120)
                _assert_matches_offline(got_c, _offline(m, params, p, n), n)
                assert np.array_equal(got_c, got_m), (S, C, got_c, got_m)

    def test_sampled_chunk_matrix(self, tiny):
        """Sampled decoding pins the rng protocol: every chunk call splits
        the SAME per-request key the way offline generate splits it once,
        so the first sampled token (and the whole decode chain after it)
        cannot depend on the chunk count."""
        _, m, params = tiny
        kw = dict(max_slots=2, max_len=64, eos_token_id=EOS, do_sample=True,
                  temperature=0.9, top_k=50, warmup=False)
        eng_c = ServingEngine(m, params, prefill_chunk=4,
                              prefix_cache_mb=0.0, **kw)
        eng_m = ServingEngine(m, params, prefill_chunk=None, **kw)
        try:
            n = 10
            rng = np.random.default_rng(12)
            for S in (5, 13, 21):
                p = rng.integers(0, 256, size=(1, S)).astype(np.int32)
                seed = 200 + S
                got_c = eng_c.submit(p, max_new_tokens=n,
                                     seed=seed).result(timeout=120)
                got_m = eng_m.submit(p, max_new_tokens=n,
                                     seed=seed).result(timeout=120)
                ref = _offline(m, params, p, n, seed=seed, do_sample=True,
                               temperature=0.9, top_k=50)
                _assert_matches_offline(got_c, ref, n)
                assert np.array_equal(got_c, got_m), (S, got_c, got_m)
        finally:
            for e in (eng_c, eng_m):
                if e.running:
                    e.shutdown(drain=False)


class TestZeroRecompileChunked:
    def test_one_chunk_executable_for_any_length_mix(self):
        """The tentpole's acceptance bar: prompt lengths spanning what used
        to be THREE 128-bucket prefill executables (3..300, both sides of
        the chunk width) run after warmup with zero compile/trace events
        and exactly ONE cached executable each for prefill_chunk,
        restore_prefix, and decode."""
        cfg = LlamaConfig.tiny(use_flash_attention=False,
                               max_position_embeddings=512)
        m = LlamaForCausalLM(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=2, seq_len=8)
        eng = ServingEngine(m, params, max_slots=2, max_len=384,
                            eos_token_id=EOS, prefill_chunk=128,
                            prefix_cache_mb=4.0)
        rng = np.random.default_rng(3)
        try:
            with CompileWatcher() as watcher:
                reqs = []
                for i, S in enumerate((3, 9, 140, 260, 300)):
                    p = rng.integers(0, 256, size=(1, S)).astype(np.int32)
                    reqs.append(eng.submit(p, max_new_tokens=6, seed=i))
                    time.sleep(0.01)
                for r in reqs:
                    r.result(timeout=300)
        finally:
            eng.shutdown(drain=False)
        assert not watcher.events, (
            f"XLA recompiled after warmup: {watcher.events} — chunked "
            "prefill must serve every prompt length with the one "
            "fixed-shape executable")
        assert eng._prefill_chunk._cache_size() == 1
        # The paged engine's private prefix cache restores by page-table
        # aliasing on the host — it compiles NO restore program (steady
        # state is two warm executables). The dense engine (and a paged
        # engine sharing an external cache) still pins the third.
        if eng._restore_prefix is not None:
            assert eng._restore_prefix._cache_size() == 1
        assert eng._decode._cache_size() == 1


class TestChunkedScheduling:
    def test_decode_ticks_between_prefill_chunks(self):
        """Acceptance: chunked admission must not stall active streams —
        while a 12-chunk prompt prefills (admission -> first token),
        an already-decoding stream keeps committing tokens. Uses the
        deterministic per-token sleep model so the prefill window is wide
        on any host."""
        import bench

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        m = bench._sleepy_llama_cls(step_ms=1.0, per_token=True)(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        eng = ServingEngine(m, params, max_slots=2, max_len=128,
                            prefill_chunk=8, prefill_chunks_per_tick=1,
                            prefix_cache_mb=0.0)
        try:
            stamps = []
            stream = eng.submit([[5, 6, 7, 8]], max_new_tokens=120,
                                ignore_eos=True,
                                on_token=lambda t: stamps.append(time.monotonic()))
            t0 = time.monotonic()
            while len(stamps) < 3:
                assert time.monotonic() - t0 < 60, "stream never decoded"
                time.sleep(0.001)
            long_req = eng.submit(np.arange(96, dtype=np.int32)[None, :],
                                  max_new_tokens=1, ignore_eos=True)
            assert long_req.wait(60)
            mid = [s for s in stamps
                   if long_req.admitted_at < s < long_req.first_token_at]
            assert len(mid) >= 3, (
                f"only {len(mid)} stream tokens during the long prompt's "
                "12-chunk prefill: decode ticks are not interleaving")
            assert eng.serving_metrics()["prefill_chunks"] >= 12
            stream.cancel()
            stream.wait(60)
        finally:
            eng.shutdown(drain=False)


class TestPrefixCacheServing:
    def test_repeat_prompt_restores_and_matches(self, tiny):
        """A 30-token prompt (4 chunks of 8) runs cold as 4 chunk calls;
        the identical prompt again admits in exactly ONE (the final chunk
        — cached blocks hold KV, not the first token's logits) with its
        3 full chunks restored, and the tokens are identical."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, prefill_chunk=8,
                            prefix_cache_mb=4.0, warmup=False)
        try:
            p = np.arange(1, 31, dtype=np.int32)[None, :]
            out1 = eng.submit(p, max_new_tokens=6).result(timeout=120)
            s1 = eng.serving_metrics()
            assert s1["prefill_chunks"] == 4
            assert s1["prefix_cache_hit_chunks"] == 0
            assert len(eng.prefix_cache) == 3  # full chunks 0..2 stored
            out2 = eng.submit(p, max_new_tokens=6).result(timeout=120)
            s2 = eng.serving_metrics()
            assert np.array_equal(out1, out2)
            _assert_matches_offline(out1, _offline(m, params, p, 6), 6)
            assert s2["prefill_chunks"] == 5  # the repeat cost ONE chunk
            assert s2["prefix_cache_hit_chunks"] == 3
            assert s2["prefix_cache_hit_rate"] == 0.5  # 3 hits / 6 lookups
            assert s2["prefix_cache_restored_bytes"] > 0
            assert s2["prefix_cache_entries"] == 3
            assert s2["prefix_cache_bytes"] == eng.prefix_cache.nbytes > 0
        finally:
            eng.shutdown(drain=False)


class TestAdmissionScreening:
    def test_idle_pop_screens_cancelled_and_expired(self, tiny):
        """Regression: the idle path used to admit its popped request
        without re-checking cancel/deadline. A request cancelled (or
        expired) while the engine idles must finish WITHOUT taking a slot
        — no tokens, no admit counters."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=64,
                            eos_token_id=EOS, warmup=False)
        try:
            r = Request([[1, 2]], max_new_tokens=4)
            r.cancel()
            eng.submit(request=r)
            assert r.wait(30)
            assert r.status is RequestStatus.CANCELLED and r.tokens == []
            r2 = eng.submit([[3]], max_new_tokens=4, timeout=0.0)
            assert r2.wait(30)
            assert r2.status is RequestStatus.TIMED_OUT and r2.tokens == []
            s = eng.serving_metrics()
            assert s["requests_admitted"] == 0
            assert s["requests_cancelled"] == 1
            assert s["requests_timed_out"] == 1
        finally:
            eng.shutdown(drain=False)

    def test_request_handles_are_single_use(self, engine):
        r = engine.submit([[2, 4]], max_new_tokens=2)
        r.result(timeout=120)
        with pytest.raises(ValueError, match="single-use"):
            engine.submit(request=r)
        fresh = Request([[6]], max_new_tokens=2)
        engine.submit(request=fresh)
        with pytest.raises(ValueError, match="single-use"):
            engine.submit(request=fresh)  # in flight: equally stale
        fresh.wait(120)


class TestSchedulingSemantics:
    @staticmethod
    def _wait_status(req, status, timeout=60.0):
        t0 = time.monotonic()
        while req.status is not status:
            if time.monotonic() - t0 > timeout:
                raise AssertionError(f"{req} never reached {status}")
            time.sleep(0.002)

    def test_backpressure_and_cancel(self, slow_engine):
        """max_slots=1, max_queued=1: the third concurrent submit must
        bounce (QueueFull + rejected counter); cancelling then reaps both
        the running and the queued request."""
        rejected_before = slow_engine.serving_metrics()["requests_rejected"]
        r_run = slow_engine.submit([[1]], max_new_tokens=30)
        self._wait_status(r_run, RequestStatus.RUNNING)
        r_queued = slow_engine.submit([[2]], max_new_tokens=30)
        with pytest.raises(QueueFull):
            slow_engine.submit([[3]], max_new_tokens=5)
        assert slow_engine.serving_metrics()["requests_rejected"] == rejected_before + 1

        r_queued.cancel()
        r_run.cancel()
        assert r_run.wait(60) and r_queued.wait(60)
        assert r_run.status is RequestStatus.CANCELLED
        assert r_queued.status is RequestStatus.CANCELLED
        assert len(r_run.tokens) < 30  # actually stopped mid-decode
        with pytest.raises(RuntimeError, match="cancelled"):
            r_queued.result()

    def test_timeout_running_request(self, slow_engine):
        r = slow_engine.submit([[1]], max_new_tokens=30, timeout=0.08)
        assert r.wait(60)
        assert r.status is RequestStatus.TIMED_OUT
        assert 1 <= len(r.tokens) < 30  # partial progress, then the deadline

    def test_timeout_queued_request(self, slow_engine):
        r_run = slow_engine.submit([[1]], max_new_tokens=30)
        self._wait_status(r_run, RequestStatus.RUNNING)
        r = slow_engine.submit([[2]], max_new_tokens=5, timeout=0.05)
        time.sleep(0.06)
        r_run.cancel()  # frees the slot; the expired request must NOT run
        assert r.wait(60)
        assert r.status is RequestStatus.TIMED_OUT and r.tokens == []
        r_run.wait(60)

    def test_error_isolation(self, engine, tiny):
        """A raising on_token callback fails ITS request only: the slot
        frees and concurrently decoding requests still finish exact."""
        _, m, params = tiny
        boom = RuntimeError("consumer went away")

        def bad_cb(tok):
            if bad_cb.n >= 2:
                raise boom
            bad_cb.n += 1

        bad_cb.n = 0
        r_bad = engine.submit(PROMPTS[0], max_new_tokens=10, on_token=bad_cb)
        r_ok = engine.submit(PROMPTS[2], max_new_tokens=10)
        assert r_bad.wait(120) and r_ok.wait(120)
        assert r_bad.status is RequestStatus.FAILED and r_bad.error is boom
        with pytest.raises(RuntimeError, match="failed"):
            r_bad.result()
        n = 10
        _assert_matches_offline(r_ok.result(), _offline(m, params, PROMPTS[2], n), n)

    def test_submit_validation(self, engine):
        with pytest.raises(ValueError, match="empty prompt"):
            engine.submit(np.zeros((1, 0), np.int32))
        with pytest.raises(ValueError, match="max_len"):
            engine.submit([[1, 2, 3]], max_new_tokens=62)  # 3 + 62 > 64


class TestLifecycle:
    def test_shutdown_drains_and_flushes_saves(self, tiny, monkeypatch):
        """shutdown(drain=True) finishes every accepted request, then blocks
        on async checkpoint saves before returning — a serving process is
        usually the process that just trained the weights it serves."""
        from accelerate_tpu import checkpointing

        flushed = []
        monkeypatch.setattr(checkpointing, "wait_for_saves",
                            lambda: flushed.append(True))
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=2, max_len=64,
                            eos_token_id=EOS, warmup=False)
        reqs = [eng.submit(p, max_new_tokens=5) for p in PROMPTS[:3]]
        eng.shutdown(drain=True)
        assert flushed == [True]
        assert not eng.running
        for r in reqs:
            assert r.status is RequestStatus.COMPLETED and 1 <= len(r.tokens) <= 5
        with pytest.raises(RuntimeError, match="not accepting"):
            eng.submit([[1]])

    def test_shutdown_without_drain_cancels(self, tiny):
        import bench

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        m = bench._sleepy_llama_cls(step_ms=10.0)(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        eng = ServingEngine(m, params, max_slots=1, max_len=32, warmup=False)
        r1 = eng.submit([[1]], max_new_tokens=30)
        r2 = eng.submit([[2]], max_new_tokens=30)
        t0 = time.monotonic()
        while r1.status is not RequestStatus.RUNNING:
            assert time.monotonic() - t0 < 60
            time.sleep(0.002)
        eng.shutdown(drain=False)
        assert r1.status is RequestStatus.CANCELLED
        assert r2.status is RequestStatus.CANCELLED

    def test_preemption_drain(self, tiny):
        """With an accelerator reporting preemption, the engine finishes
        what is decoding, cancels what is queued, and exits — flushing
        work inside the notice window instead of taking more."""
        _, m, params = tiny
        acc = types.SimpleNamespace(policy=None, mesh=None,
                                    preemption_requested=False)
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, accelerator=acc, warmup=False)
        running = [eng.submit(p, max_new_tokens=45, ignore_eos=True)
                   for p in PROMPTS[:3]]
        queued = eng.submit(PROMPTS[3], max_new_tokens=45)
        t0 = time.monotonic()
        while eng._slots.active_slots < 3:  # all three lanes decoding
            assert time.monotonic() - t0 < 120
            time.sleep(0.001)
        acc.preemption_requested = True
        t0 = time.monotonic()
        while eng.running:
            assert time.monotonic() - t0 < 120, "engine did not exit on preemption"
            time.sleep(0.005)
        for r in running:
            assert r.status is RequestStatus.COMPLETED and len(r.tokens) == 45
        assert queued.status is RequestStatus.CANCELLED
        with pytest.raises(RuntimeError, match="not accepting"):
            eng.submit([[1]])

    def test_rejects_model_without_kv_cache(self):
        import flax.linen as nn

        dense = nn.Dense(4)
        params = dense.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))["params"]
        with pytest.raises(TypeError, match="KV cache"):
            ServingEngine(dense, params, autostart=False)


class TestMetrics:
    def test_serving_metrics_coherent(self, engine):
        """Run after the exactness/streaming tests on the shared engine:
        the cumulative counters must describe a working service."""
        s = engine.serving_metrics()
        assert s["requests_admitted"] >= 4
        assert s["requests_completed"] >= 4
        assert s["requests_submitted"] >= s["requests_admitted"]
        assert s["ttft_ms"] > 0 and s["ttft_ms_p95"] >= s["ttft_ms_p50"] > 0
        assert s["decode_tokens_per_sec"] > 0
        assert 0 < s["slot_occupancy"] <= 1.0
        assert 0 < s["batch_efficiency"] <= s["slot_occupancy"]
        assert s["tokens_emitted"] == s["decode_tokens"] + s["requests_admitted"]

    def test_accelerator_wiring(self, tiny):
        """An engine built with accelerator= shares the accelerator's
        ServingStats, so Accelerator.log(include_serving=True) and
        serving_metrics() see this engine without extra plumbing."""
        from accelerate_tpu import Accelerator
        from accelerate_tpu.tracking import with_serving_metrics

        acc = Accelerator()
        acc.serving_stats.record_submit(queue_depth=0)
        assert acc.serving_metrics()["requests_submitted"] == 1
        payload = with_serving_metrics({"loss": 1.0}, acc.serving_stats)
        assert payload["loss"] == 1.0
        assert payload["serving/requests_submitted"] == 1
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=64,
                            accelerator=acc, autostart=False)
        assert eng.stats is acc.serving_stats


@pytest.mark.slow
class TestSoak:
    def test_sustained_mixed_load(self, engine, tiny):
        """Soak: 40 mixed-length requests with jittered arrivals; every
        stream completes, every stream is exact, and the counters balance."""
        _, m, params = tiny
        rng = np.random.default_rng(0)
        before = engine.serving_metrics()
        work = []
        for i in range(40):
            S = int(rng.integers(1, 24))
            n = int(rng.integers(1, 20))
            p = rng.integers(0, 256, size=(1, S)).astype(np.int32)
            work.append((p, n, engine.submit(p, max_new_tokens=n)))
            time.sleep(float(rng.random()) * 0.004)
        for p, n, r in work:
            _assert_matches_offline(r.result(timeout=300),
                                    _offline(m, params, p, n), n)
        after = engine.serving_metrics()
        assert after["requests_completed"] - before["requests_completed"] == 40
        assert after["requests_admitted"] - before["requests_admitted"] == 40

    def test_sustained_mixed_load_chunked_with_prefix_hits(self, tiny):
        """Chunked soak: 30 jittered requests drawn from a small prompt
        pool (so multi-chunk prompts repeat and the prefix cache actually
        fires mid-load); every stream exact, hits observed."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, prefill_chunk=4,
                            prefix_cache_mb=2.0)
        try:
            rng = np.random.default_rng(1)
            pool = [rng.integers(0, 256, size=(1, S)).astype(np.int32)
                    for S in (1, 3, 6, 9, 14, 23)]
            work = []
            for _ in range(30):
                p = pool[int(rng.integers(len(pool)))]
                n = int(rng.integers(1, 16))
                work.append((p, n, eng.submit(p, max_new_tokens=n)))
                time.sleep(float(rng.random()) * 0.004)
            for p, n, r in work:
                _assert_matches_offline(r.result(timeout=300),
                                        _offline(m, params, p, n), n)
            s = eng.serving_metrics()
            assert s["requests_completed"] == 30
            assert s["prefix_cache_hit_chunks"] > 0
        finally:
            eng.shutdown(drain=False)


class TestLifecycleEdges:
    """Lifecycle races hardened for the gateway: submits outside the
    accepting window fail fast, and producers blocked on a full admission
    queue are woken (with an error) when the engine stops instead of
    hanging for their full block_timeout."""

    def test_submit_before_start_raises_immediately(self, tiny):
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=32,
                            eos_token_id=EOS, autostart=False, warmup=False)
        try:
            with pytest.raises(RuntimeError, match="not accepting"):
                eng.submit([[1, 2]], max_new_tokens=2)
            eng.start()
            r = eng.submit([[1, 2]], max_new_tokens=2)
            assert r.wait(120)
        finally:
            eng.shutdown(drain=False)
        with pytest.raises(RuntimeError, match="not accepting"):
            eng.submit([[1, 2]], max_new_tokens=2)

    def test_submit_after_shutdown_raises_even_with_block(self, tiny):
        """block=True must not buy a stopped engine a grace period: the
        error is immediate, not a block_timeout-long hang."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=1, max_len=32,
                            eos_token_id=EOS, warmup=False)
        eng.shutdown(drain=True)
        t0 = time.monotonic()
        with pytest.raises(RuntimeError, match="not accepting"):
            eng.submit([[1]], max_new_tokens=2, block=True, block_timeout=30)
        assert time.monotonic() - t0 < 5.0

    def test_queue_close_wakes_blocked_put(self):
        """Unit: a producer parked in put(block=True) on a FULL queue is
        woken by close() with QueueClosed — not left to ride out its
        timeout; items already accepted stay drainable."""
        q = AdmissionQueue(max_queued=1)
        q.put("held")
        woke = {}

        def producer():
            t0 = time.monotonic()
            try:
                q.put("late", block=True, timeout=30.0)
                woke["outcome"] = "accepted"
            except QueueClosed:
                woke["outcome"] = "closed"
            except QueueFull:
                woke["outcome"] = "full"
            woke["elapsed"] = time.monotonic() - t0

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.05)  # parked in the condition wait
        q.close()
        t.join(timeout=10)
        assert not t.is_alive()
        assert woke["outcome"] == "closed"
        assert woke["elapsed"] < 5.0
        assert q.get_nowait() == "held"  # close() does not eat the backlog
        with pytest.raises(QueueClosed):
            q.put("post-close")

    @pytest.mark.slow
    def test_engine_stop_wakes_blocked_submit(self):
        """End-to-end: a submit(block=True) stuck behind a full admission
        queue errors out promptly when the engine shuts down underneath
        it."""
        import bench

        cfg = LlamaConfig.tiny(use_flash_attention=False)
        m = bench._sleepy_llama_cls(step_ms=10.0)(cfg)
        params = m.init_params(jax.random.PRNGKey(0), batch_size=1, seq_len=8)
        eng = ServingEngine(m, params, max_slots=1, max_len=32, max_queued=1)
        r_run = eng.submit([[1]], max_new_tokens=30)
        deadline = time.monotonic() + 60
        while r_run.status is not RequestStatus.RUNNING \
                and time.monotonic() < deadline:
            time.sleep(0.005)  # in its slot -> the 1-deep queue is free
        r_queued = eng.submit([[2]], max_new_tokens=30)
        outcome = {}

        def producer():
            t0 = time.monotonic()
            try:
                eng.submit([[3]], max_new_tokens=5, block=True,
                           block_timeout=60.0)
                outcome["kind"] = "accepted"
            except QueueFull:
                outcome["kind"] = "full"
            except RuntimeError:
                outcome["kind"] = "stopped"
            outcome["elapsed"] = time.monotonic() - t0

        t = threading.Thread(target=producer)
        t.start()
        time.sleep(0.1)  # parked in the queue's not_full wait
        eng.shutdown(drain=False)
        t.join(timeout=15)
        assert not t.is_alive(), "blocked submit hung past engine shutdown"
        assert outcome["kind"] == "stopped"
        assert outcome["elapsed"] < 10.0
        for r in (r_run, r_queued):
            assert r.wait(60)
            assert r.status in (RequestStatus.CANCELLED, RequestStatus.FAILED)

    def test_prefix_cache_oversize_put_rejected_without_eviction(self):
        """An oversize block must bounce at the door — never by evicting
        the whole (useful) cache first."""
        cache = PrefixCache(capacity_bytes=1024)
        cache.put(("a",), "blockA", 400)
        cache.put(("b",), "blockB", 400)
        assert cache.oversize_rejects == 0
        cache.put(("huge",), "big", 4096)  # > whole capacity
        assert cache.oversize_rejects == 1
        assert cache.match([("huge",)]) == []
        # The resident entries survived the oversize attempt untouched.
        assert len(cache) == 2 and cache.nbytes == 800
        assert cache.match([("a",)]) == ["blockA"]
        assert cache.match([("b",)]) == ["blockB"]
        assert cache.evictions == 0
        cache.clear()
        assert cache.oversize_rejects == 0


class TestConcurrentSubmit:
    @pytest.mark.slow
    def test_32_threads_no_lost_or_duplicated_requests(self, tiny):
        """32 producer threads x 4 submits each hammer one engine; queue
        bounce (QueueFull) is legal under the bounded queue, but every
        ACCEPTED request must complete exactly once with an exact stream,
        and the admission counters must balance to the thread-side tally."""
        _, m, params = tiny
        eng = ServingEngine(m, params, max_slots=3, max_len=64,
                            eos_token_id=EOS, max_queued=256)
        n_threads, per_thread, n_tok = 32, 4, 6
        refs = {i: _offline(m, params, p, n_tok)
                for i, p in enumerate(PROMPTS)}
        accepted = [[] for _ in range(n_threads)]
        bounced = [0] * n_threads
        start = threading.Barrier(n_threads)

        def worker(tid):
            start.wait()
            for j in range(per_thread):
                pi = (tid + j) % len(PROMPTS)
                try:
                    r = eng.submit(PROMPTS[pi], max_new_tokens=n_tok)
                except QueueFull:
                    bounced[tid] += 1
                    continue
                accepted[tid].append((pi, r))

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(n_threads)]
        before = eng.serving_metrics()
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert not any(t.is_alive() for t in threads)
            flat = [ar for per in accepted for ar in per]
            for pi, r in flat:
                assert r.wait(300)
                assert r.status is RequestStatus.COMPLETED
                _assert_matches_offline(r.tokens, refs[pi], n_tok)
            after = eng.serving_metrics()
            n_acc = len(flat)
            n_rej = sum(bounced)
            assert n_acc + n_rej == n_threads * per_thread
            assert after["requests_submitted"] - before["requests_submitted"] == n_acc
            assert after["requests_completed"] - before["requests_completed"] == n_acc
            assert after["requests_rejected"] - before["requests_rejected"] == n_rej
            # One terminal transition per handle: result() replays, and
            # output_ids() is exactly prompt + the streamed tokens.
            for pi, r in flat:
                full = r.output_ids()
                S = PROMPTS[pi].shape[1]
                assert full.shape == (1, S + len(r.tokens))
                assert list(full[0, S:]) == [int(t) for t in r.tokens]
        finally:
            eng.shutdown(drain=False)
