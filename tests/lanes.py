"""Test-lane partition: the single source of truth for CI/dev test splits.

Mirrors the reference's budgeted lanes (reference: Makefile:26-58 and
.github/workflows/test.yml:22-38) adapted to this box: one alphabetical
25-minute run hides a failure behind 20 minutes of unrelated tests, so the
suite splits into four lanes a developer can run by cost.

    make test-fast          # unit core            (~5 min budget)
    make test-models        # model zoo + HF parity (~12 min)
    make test-subproc       # CLI + example scripts (~12 min)
    make test-multiprocess  # real jax.distributed worlds (~8 min)
    make test-all           # everything, no -x

Usage as a module:  python tests/lanes.py <lane>  prints the file list.
``test_lanes_partition`` (in test_state.py's fast lane) asserts every
``tests/test_*.py`` belongs to exactly one lane, so new files must be
assigned here or the fast lane fails immediately.
"""

from __future__ import annotations

import os
import sys

#: lane -> (budget_minutes, [test files])
LANES: dict[str, tuple[int, list[str]]] = {
    "fast": (5, [
        "test_accelerator.py",
        "test_bench.py",
        "test_checkpointing.py",
        "test_data_loader.py",
        "test_env_memory_utils.py",
        "test_flash_attention.py",
        "test_fused_loss.py",
        "test_lanes.py",
        "test_local_sgd_inference.py",
        "test_menu.py",
        "test_moe.py",
        "test_native.py",
        "test_operations.py",
        "test_other_utils.py",
        "test_packing.py",
        "test_perf_guards.py",
        "test_precision.py",
        "test_ring_attention.py",
        "test_state.py",
        "test_tracking.py",
        "test_zero_sharding.py",
    ]),
    "models": (12, [
        "test_adapters.py",
        "test_big_modeling.py",
        "test_fp8.py",
        "test_generation.py",
        "test_hf_interop.py",
        "test_host_offload.py",
        "test_loadgen.py",
        "test_loadtest_smoke.py",
        "test_memory_properties.py",
        "test_models.py",
        "test_observability.py",
        "test_pipeline.py",
        "test_quantization.py",
        "test_serving.py",
        "test_serving_async.py",
        "test_serving_control.py",
        "test_serving_gateway.py",
        "test_serving_mesh.py",
        "test_serving_paged.py",
        "test_serving_quantized.py",
        "test_serving_supervisor.py",
    ]),
    "subproc": (12, [
        "test_cli.py",
        "test_cli_deadbackend.py",
        "test_watch_rehearsal.py",
        "test_examples.py",
    ]),
    "multiprocess": (8, [
        "test_multiprocess.py",
    ]),
}


def lane_files(lane: str) -> list[str]:
    here = os.path.dirname(os.path.abspath(__file__))
    _, files = LANES[lane]
    return [os.path.join("tests", f) for f in files if os.path.exists(os.path.join(here, f))]


def all_assigned() -> set[str]:
    return {f for _, files in LANES.values() for f in files}


def main() -> int:
    if len(sys.argv) != 2 or sys.argv[1] not in LANES:
        print(f"usage: python tests/lanes.py {{{','.join(LANES)}}}", file=sys.stderr)
        return 2
    print(" ".join(lane_files(sys.argv[1])))
    return 0


if __name__ == "__main__":
    sys.exit(main())
